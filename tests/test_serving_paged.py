"""Block-paged serving engine (``models/serving.py:PagedServer``):
exact greedy parity with solo decode across dense / int8-KV / pallas /
tensor-parallel stacks, chunked-prefill interleaving, prefix-sharing
COW semantics, page-ledger hygiene through retire/abort/reset, and the
pages-free admission seams (HTTP ingress, gang driver)."""

import json
import threading
import urllib.request

import jax
import jax.numpy as jnp
import pytest

import tests._jax_cpu  # noqa: F401

from dcos_commons_tpu.models import llama, serving
from dcos_commons_tpu.models.ingress import ServingFrontend
from dcos_commons_tpu.models.serving_gang import GangServingDriver


def _cfg(**kw):
    return llama.LlamaConfig.tiny(n_layers=2, max_seq=64,
                                  attn_impl="dense", **kw)


def _solo(cfg, params, prompt, steps, mesh=None):
    toks = llama.generate_stepwise(cfg, params,
                                   jnp.asarray([prompt], jnp.int32),
                                   steps, mesh=mesh)
    return [int(t) for t in toks[0]]


def _prompt(seed, n, vocab):
    return [int(t) for t in jax.random.randint(
        jax.random.key(seed), (n,), 0, vocab)]


def _flash_cfg(n_kv_heads=1):
    kw = dict(vocab_size=128, dim=256, n_layers=2, n_heads=2,
              n_kv_heads=n_kv_heads, ffn_dim=256, max_seq=128,
              remat=False)
    cfg = llama.LlamaConfig(**kw, attn_impl="dense", kv_quant=True,
                            decode_attn="flash_interpret")
    params = llama.quantize_params(llama.init_params(
        llama.LlamaConfig(**kw), jax.random.key(0)))
    return cfg, params


# ----------------------------------------------------------------- parity


def test_paged_streams_match_solo_decode():
    """Mixed-length requests through the paged engine (forcing stream
    reuse) each emit exactly their solo greedy stream — windowed decode
    included."""
    cfg = _cfg()
    params = llama.init_params(cfg, jax.random.key(0))
    reqs = [{"prompt": _prompt(40 + i, n, cfg.vocab_size),
             "max_new": m, "request_id": i}
            for i, (n, m) in enumerate([(8, 6), (5, 9), (12, 4),
                                        (20, 7)])]
    want = {r["request_id"]: _solo(cfg, params, r["prompt"],
                                   r["max_new"]) for r in reqs}
    got = serving.PagedServer(cfg, params, slots=2, page_size=16,
                              prefill_chunk=8).drain(
        [dict(r) for r in reqs])
    assert got == want, (got, want)
    windowed = serving.PagedServer(cfg, params, slots=2, page_size=16,
                                   prefill_chunk=8).drain(
        [dict(r) for r in reqs], decode_window=4)
    assert windowed == want, (windowed, want)


def test_paged_parity_without_prefix_cache():
    """Shared-prefix prompts with sharing DISABLED still match solo —
    the radix is an optimization, never a correctness dependency."""
    cfg = _cfg()
    params = llama.init_params(cfg, jax.random.key(0))
    base = _prompt(50, 20, cfg.vocab_size)
    reqs = [{"prompt": base, "max_new": 5, "request_id": "a"},
            {"prompt": base[:16] + _prompt(51, 4, cfg.vocab_size),
             "max_new": 6, "request_id": "b"},
            {"prompt": base, "max_new": 4, "request_id": "c"}]
    server = serving.PagedServer(cfg, params, slots=2, page_size=8,
                                 prefill_chunk=8, prefix_cache=False)
    got = server.drain([dict(r) for r in reqs])
    for r in reqs:
        want = _solo(cfg, params, r["prompt"], r["max_new"])
        assert got[r["request_id"]] == want, (r["request_id"],)
    assert server.page_stats()["prefix_hits"] == 0


def test_admission_blocks_on_pages_not_slots():
    """The paged engine admits on PAGES free: with the pool sized for
    two full streams, four free slots still only admit two requests —
    and the backlog completes with exact parity once pages recycle."""
    cfg = _cfg()
    params = llama.init_params(cfg, jax.random.key(0))
    # each request spans 60 tokens -> 4 pages of 16; pool holds 8
    reqs = [{"prompt": _prompt(60 + i, 40, cfg.vocab_size),
             "max_new": 20, "request_id": i} for i in range(4)]
    server = serving.PagedServer(cfg, params, slots=4, pages=8,
                                 page_size=16, prefill_chunk=16)
    placed = server.submit_many([dict(r) for r in reqs])
    assert len(placed) == 2                      # page-bound, not slot-bound
    assert len(server.free_slots()) == 2         # slots were NOT the limit
    assert server.pages_free() == 0
    got = server.drain([dict(r) for r in reqs[len(placed):]])
    for r in reqs:
        want = _solo(cfg, params, r["prompt"], r["max_new"])
        assert got[r["request_id"]] == want, (r["request_id"],)
    assert server.ledger_violations() == []
    assert server.page_stats()["pages_in_use_peak"] == 8


def test_kv_quant_paged_parity():
    """int8 KV pages (QTensor pool) match solo int8-KV decode."""
    cfg = _cfg(kv_quant=True)
    params = llama.init_params(cfg, jax.random.key(0))
    reqs = [{"prompt": _prompt(70 + i, n, cfg.vocab_size),
             "max_new": m, "request_id": i}
            for i, (n, m) in enumerate([(8, 5), (14, 6)])]
    got = serving.PagedServer(cfg, params, slots=2, page_size=16,
                              prefill_chunk=8).drain(
        [dict(r) for r in reqs])
    for r in reqs:
        want = _solo(cfg, params, r["prompt"], r["max_new"])
        assert got[r["request_id"]] == want, (r["request_id"],)


def test_flash_interpret_paged_parity():
    """The pallas paged-decode kernel (interpret mode) + int8 KV serves
    exactly the solo stream. head_dim and page_size are both 128-aligned
    so the REAL kernel path (not the dense fallback) is exercised."""
    cfg, params = _flash_cfg()
    assert llama._use_flash_decode_paged(cfg, None, 128)
    reqs = [{"prompt": _prompt(80 + i, n, cfg.vocab_size),
             "max_new": m, "request_id": i}
            for i, (n, m) in enumerate([(8, 5), (16, 7)])]
    got = serving.PagedServer(cfg, params, slots=2, page_size=128,
                              prefill_chunk=8).drain(
        [dict(r) for r in reqs])
    for r in reqs:
        want = _solo(cfg, params, r["prompt"], r["max_new"])
        assert got[r["request_id"]] == want, (r["request_id"],)


class TestPagedServerTP:
    """Paged serving composes with tensor parallelism: streams on a
    sharded mesh equal SOLO decode on the same mesh (same reduction
    orders — see TestSlotServerTP for why the reference must also be
    sharded)."""

    def test_tp_paged_streams_match_solo_tp(self):
        from dcos_commons_tpu.parallel.mesh import MeshSpec
        cfg = _cfg()
        params = llama.init_params(cfg, jax.random.key(0))
        mesh = MeshSpec(tp=2).build(jax.devices()[:2])
        with mesh:
            sharded = llama.shard_params(params, mesh, cfg)
        reqs = [{"prompt": _prompt(90 + i, n, cfg.vocab_size),
                 "max_new": m, "request_id": i}
                for i, (n, m) in enumerate([(8, 6), (5, 9), (12, 4)])]
        want = {r["request_id"]: _solo(cfg, sharded, r["prompt"],
                                       r["max_new"], mesh=mesh)
                for r in reqs}
        got = serving.PagedServer(cfg, sharded, slots=2, page_size=16,
                                  prefill_chunk=8, mesh=mesh).drain(
            [dict(r) for r in reqs])
        assert got == want, (got, want)

    def test_tp_paged_flash_kernel_int8(self):
        """Full paged tp stack — int8 weights, int8 KV pages, pallas
        paged kernel per head shard (interpret) — matches solo on the
        same mesh."""
        from dcos_commons_tpu.parallel.mesh import MeshSpec
        cfg, params = _flash_cfg(n_kv_heads=2)
        mesh = MeshSpec(tp=2).build(jax.devices()[:2])
        with mesh:
            sharded = llama.shard_params(params, mesh, cfg)
        # same request set as test_tp_slot_flash_kernel_int8: int8
        # weights make the bf16 logit grid coarse enough for EXACT
        # argmax ties, which different (all-valid) tp reduction orders
        # break differently — these prompts are tie-free at every step
        reqs = [{"prompt": _prompt(20 + i, n, cfg.vocab_size),
                 "max_new": m, "request_id": i}
                for i, (n, m) in enumerate([(8, 5), (16, 7), (4, 3)])]
        want = {r["request_id"]: _solo(cfg, sharded, r["prompt"],
                                       r["max_new"], mesh=mesh)
                for r in reqs}
        got = serving.PagedServer(cfg, sharded, slots=2, page_size=128,
                                  prefill_chunk=8, mesh=mesh).drain(
            [dict(r) for r in reqs])
        assert got == want, (got, want)


# -------------------------------------------------------- chunked prefill


class TestChunkedPrefill:
    def test_decode_interleaves_with_long_prefill(self):
        """A long prompt prefills one fixed chunk per step while an
        already-running stream keeps emitting a token EVERY step — the
        head-of-line blocking the chunking exists to kill."""
        cfg = _cfg()
        params = llama.init_params(cfg, jax.random.key(0))
        server = serving.PagedServer(cfg, params, slots=2, page_size=8,
                                     prefill_chunk=4)
        short = _prompt(100, 4, cfg.vocab_size)
        long = _prompt(101, 33, cfg.vocab_size)
        sa = server.submit(short, max_new=24, request_id="a")
        while not server._decoding[sa]:
            server.step()
        sb = server.submit(long, max_new=8, request_id="b")
        interleaved = 0
        while server._prefill_q:
            before = len(server.requests[sa].tokens)
            server.step()
            assert len(server.requests[sa].tokens) == before + 1
            # the prefilling stream must not emit mid-prefill
            if server.requests[sb] is not None:
                assert server.requests[sb].tokens == []
            interleaved += 1
        assert interleaved >= 8        # 33 tokens / chunks of 4
        while server.requests_active():
            server.step()
        assert server.finished["a"] == _solo(cfg, params, short, 24)
        assert server.finished["b"] == _solo(cfg, params, long, 8)
        assert server.ledger_violations() == []

    def test_first_token_deferred_until_next_step(self):
        """The final chunk's sampled token stays device-resident (no
        per-request host sync); it lands in the stream at the NEXT
        step's flush, together with decode activation."""
        cfg = _cfg()
        params = llama.init_params(cfg, jax.random.key(0))
        server = serving.PagedServer(cfg, params, slots=1, page_size=8,
                                     prefill_chunk=8)
        prompt = _prompt(102, 6, cfg.vocab_size)
        slot = server.submit(prompt, max_new=4, request_id="x")
        server.step()                      # runs the one prefill chunk
        assert slot in server._pending_first        # deferred on device
        assert server.requests[slot].tokens == []   # nothing synced yet
        assert not server._decoding[slot]
        server.step()                      # flush + first decode step
        want = _solo(cfg, params, prompt, 4)
        assert server.requests[slot].tokens == want[:2]


# ------------------------------------------------- prefix sharing + ledger


class TestPrefixSharingAndLedger:
    def test_retire_adopts_prefix_then_second_request_shares(self):
        """A retired stream's full prompt pages live on in the radix;
        an identical prompt re-served shares them (prefix_hits) and
        still emits the exact solo stream."""
        cfg = _cfg()
        params = llama.init_params(cfg, jax.random.key(0))
        server = serving.PagedServer(cfg, params, slots=2, page_size=8,
                                     prefill_chunk=8)
        prompt = _prompt(110, 20, cfg.vocab_size)
        want = _solo(cfg, params, prompt, 6)
        first = server.drain([{"prompt": prompt, "max_new": 6,
                               "request_id": "a"}])
        assert first["a"] == want
        stats = server.page_stats()
        assert stats["prefix_hits"] == 0
        # 2 full prompt pages (tokens 0..16) adopted into the radix
        assert stats["pages_in_use"] == 2
        second = server.drain([{"prompt": list(prompt), "max_new": 6,
                                "request_id": "b"}])
        assert second["b"] == want
        stats = server.page_stats()
        assert stats["prefix_hits"] == 1
        assert stats["prefix_shared_pages"] == 2
        assert server.ledger_violations() == []

    def test_cow_boundary_page_stays_private(self):
        """A prompt that is a PARTIAL-page extension of a cached prefix
        gets an eager private copy of the boundary page — its stream
        matches solo, and decoding into the copy never corrupts the
        cached original (the original prompt re-serves exactly)."""
        cfg = _cfg()
        params = llama.init_params(cfg, jax.random.key(0))
        server = serving.PagedServer(cfg, params, slots=2, page_size=8,
                                     prefill_chunk=8)
        a = _prompt(111, 16, cfg.vocab_size)
        b = a[:13]                     # full page + 5-token partial tail
        want_a = _solo(cfg, params, a, 6)
        want_b = _solo(cfg, params, b, 6)
        assert server.drain([{"prompt": a, "max_new": 6,
                              "request_id": "a"}])["a"] == want_a
        got_b = server.drain([{"prompt": b, "max_new": 6,
                               "request_id": "b"}])
        assert got_b["b"] == want_b
        assert server.page_stats()["prefix_hits"] == 1   # page 1 shared
        # the cached original is untouched by b's COW + decode writes
        got_a2 = server.drain([{"prompt": list(a), "max_new": 6,
                                "request_id": "a2"}])
        assert got_a2["a2"] == want_a
        assert server.ledger_violations() == []

    def test_abort_returns_every_page(self):
        cfg = _cfg()
        params = llama.init_params(cfg, jax.random.key(0))
        server = serving.PagedServer(cfg, params, slots=2, page_size=8,
                                     prefill_chunk=4, prefix_cache=False)
        server.submit(_prompt(112, 20, cfg.vocab_size), max_new=10)
        server.submit(_prompt(113, 6, cfg.vocab_size), max_new=10)
        for _ in range(3):
            server.step()              # one mid-prefill, one decoding
        assert server.abort_active() == 2
        assert server.pages_free() == server.total_pages
        assert server.ledger_violations() == []
        assert not server._prefill_q and not server._pending_first

    def test_reset_rebuilds_clean_and_serves_again(self):
        cfg = _cfg()
        params = llama.init_params(cfg, jax.random.key(0))
        server = serving.PagedServer(cfg, params, slots=2, page_size=8,
                                     prefill_chunk=4)
        prompt = _prompt(114, 12, cfg.vocab_size)
        server.submit(prompt, max_new=8, request_id="pre")
        server.step()
        server.reset()
        assert server.pages_free() == server.total_pages
        assert server.ledger_violations() == []
        assert server.page_stats()["prefix_hits"] == 0
        got = server.drain([{"prompt": prompt, "max_new": 8,
                             "request_id": "post"}])
        assert got["post"] == _solo(cfg, params, prompt, 8)

    def test_infeasible_configs_rejected_loudly(self):
        cfg = _cfg()
        params = llama.init_params(cfg, jax.random.key(0))
        with pytest.raises(ValueError, match="must divide"):
            serving.PagedServer(cfg, params, slots=2, page_size=24)
        with pytest.raises(ValueError, match=">= 1 page"):
            serving.PagedServer(cfg, params, slots=2, pages=0,
                                page_size=16)
        server = serving.PagedServer(cfg, params, slots=2, pages=2,
                                     page_size=16)
        # 60 tokens need 4 pages; the pool permanently holds 2
        with pytest.raises(ValueError, match="pages"):
            server.submit(_prompt(115, 40, cfg.vocab_size), max_new=20)


# ---------------------------------------------------------------- seams


def _post(port, payload, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


class TestPagedSeams:
    def test_ingress_backlog_drains_under_page_pressure(self):
        """Four concurrent HTTP clients against a pool that fits two
        streams: the ingress re-offers the page-blocked tail until pages
        recycle, and every client gets its exact solo stream."""
        cfg = _cfg()
        params = llama.init_params(cfg, jax.random.key(0))
        engine = serving.PagedServer(cfg, params, slots=4, pages=8,
                                     page_size=16, prefill_chunk=16)
        fe = ServingFrontend(engine, port=0, host="127.0.0.1").start()
        try:
            prompts = [_prompt(120 + i, 40, cfg.vocab_size)
                       for i in range(4)]
            results = [None] * 4

            def hit(i):
                results[i] = _post(fe.port, {"prompt": prompts[i],
                                             "max_new": 20})

            threads = [threading.Thread(target=hit, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=600)
            for i in range(4):
                status, body = results[i]
                assert status == 200
                want = _solo(cfg, params, prompts[i], 20)
                assert body["tokens"] == want, (i,)
        finally:
            fe.stop()
        assert engine.ledger_violations() == []

    def test_gang_driver_single_process_paged(self):
        """The lock-step gang loop (num_processes=1 degenerate) drives
        the paged engine behind real HTTP."""
        cfg = _cfg()
        params = llama.init_params(cfg, jax.random.key(0))
        engine = serving.PagedServer(cfg, params, slots=2, page_size=16,
                                     prefill_chunk=8,
                                     key=jax.random.key(0))
        fe = ServingFrontend(engine, port=0, host="127.0.0.1")
        fe.start(drive=False)
        driver = GangServingDriver(engine, fe, num_processes=1,
                                   process_id=0, decode_window=4)
        t = threading.Thread(target=driver.run, daemon=True)
        t.start()
        try:
            got = {}
            for i in range(3):
                p = _prompt(130 + i, 5 + i, cfg.vocab_size)
                status, body = _post(fe.port, {"prompt": p,
                                               "max_new": 6})
                assert status == 200
                got[i] = (body["tokens"], _solo(cfg, params, p, 6))
            for i, (tokens, want) in got.items():
                assert tokens == want, (i, tokens, want)
        finally:
            driver.stop()
            t.join(timeout=10)
            fe.stop()
        assert engine.ledger_violations() == []


# ------------------------------------------- round 18: MoE + ring prefill


class TestServingArithmetic:
    """Round-18 arithmetic through the paged engine: routed-FFN (MoE)
    decode and sequence-parallel ring prefill, both pinned token-exact
    against their dense/single-host references with a clean ledger."""

    def test_moe_paged_streams_match_stepwise_moe(self):
        """Dropless MoE serving == the stepwise MoE reference for every
        stream (routing is grouping-free under the dropless contract),
        windowed decode included."""
        from dcos_commons_tpu.parallel.moe import MoEConfig, dropless
        cfg = _cfg()
        moe = dropless(MoEConfig(num_experts=4))
        params = llama.init_moe_params(cfg, 4, jax.random.key(0))
        reqs = [{"prompt": _prompt(150 + i, n, cfg.vocab_size),
                 "max_new": m, "request_id": i}
                for i, (n, m) in enumerate([(8, 6), (5, 9), (17, 4)])]
        want = {}
        for r in reqs:
            toks = llama.generate_stepwise_moe(
                cfg, params, jnp.asarray([r["prompt"]], jnp.int32),
                r["max_new"], moe)
            want[r["request_id"]] = [int(t) for t in toks[0]]
        server = serving.PagedServer(cfg, params, slots=2, page_size=16,
                                     prefill_chunk=8, moe=moe)
        got = server.drain([dict(r) for r in reqs])
        assert got == want, (got, want)
        assert server.ledger_violations() == []
        windowed = serving.PagedServer(
            cfg, params, slots=2, page_size=16, prefill_chunk=8,
            moe=moe).drain([dict(r) for r in reqs], decode_window=4)
        assert windowed == want, (windowed, want)

    def test_moe_paged_expert_parallel_mesh_parity(self):
        """The same streams through an ep-sharded mesh (the expert-
        parallel all-to-all dispatch) stay token-exact — the sharded
        path is bitwise the local path."""
        from dcos_commons_tpu.parallel.mesh import MeshSpec
        from dcos_commons_tpu.parallel.moe import MoEConfig, dropless
        cfg = _cfg()
        moe = dropless(MoEConfig(num_experts=4))
        params = llama.init_moe_params(cfg, 4, jax.random.key(0))
        mesh = MeshSpec(ep=4, dp=2).build()
        reqs = [{"prompt": _prompt(160 + i, n, cfg.vocab_size),
                 "max_new": m, "request_id": i}
                for i, (n, m) in enumerate([(9, 5), (6, 7)])]
        want = {}
        for r in reqs:
            toks = llama.generate_stepwise_moe(
                cfg, params, jnp.asarray([r["prompt"]], jnp.int32),
                r["max_new"], moe)
            want[r["request_id"]] = [int(t) for t in toks[0]]
        server = serving.PagedServer(cfg, params, slots=2, page_size=16,
                                     prefill_chunk=8, mesh=mesh, moe=moe)
        got = server.drain([dict(r) for r in reqs])
        assert got == want, (got, want)
        assert server.page_stats()["moe"]["experts"] == 4
        assert server.ledger_violations() == []

    def test_moe_requires_router_params_and_vice_versa(self):
        from dcos_commons_tpu.parallel.moe import MoEConfig, dropless
        cfg = _cfg()
        dense = llama.init_params(cfg, jax.random.key(0))
        routed = llama.init_moe_params(cfg, 4, jax.random.key(0))
        with pytest.raises(ValueError, match="router"):
            serving.PagedServer(cfg, dense, slots=2, page_size=16,
                                moe=dropless(MoEConfig(num_experts=4)))
        with pytest.raises(ValueError, match="moe"):
            serving.PagedServer(cfg, routed, slots=2, page_size=16)

    def test_moe_engine_rejects_draft_arming(self):
        """Spec decode's K-wide verify would route a k-token group that
        the committed history routed one token at a time — arming must
        refuse with a coded error, not emit drifted tokens."""
        from dcos_commons_tpu.parallel.moe import MoEConfig, dropless
        cfg = _cfg()
        moe = dropless(MoEConfig(num_experts=4))
        params = llama.init_moe_params(cfg, 4, jax.random.key(0))
        server = serving.PagedServer(cfg, params, slots=2, page_size=16,
                                     moe=moe)
        dcfg = llama.LlamaConfig.tiny(n_layers=1, max_seq=64,
                                      attn_impl="dense")
        dparams = llama.init_params(dcfg, jax.random.key(1))
        from dcos_commons_tpu.models.speculative import DraftIncompatible
        with pytest.raises(DraftIncompatible) as ei:
            server.arm_draft(dcfg, dparams, k=4)
        assert ei.value.code == "draft_moe_engine"

    def test_ring_prefill_matches_single_host_reference(self):
        """Prompts over the ring threshold prefill in ONE tick via the
        sp-gang ring path and stay token-exact with single-host solo
        decode — at a shape where the longest prompt's pad hits
        max_seq exactly (the chunk-window-overrun regression class:
        positions near max_seq must not re-clamp rope)."""
        from dcos_commons_tpu.parallel.mesh import MeshSpec
        cfg = _cfg()                          # max_seq = 64
        params = llama.init_params(cfg, jax.random.key(0))
        mesh = MeshSpec(sp=4, dp=2).build()
        reqs = [{"prompt": _prompt(170 + i, n, cfg.vocab_size),
                 "max_new": m, "request_id": i}
                for i, (n, m) in enumerate([(60, 4), (33, 6), (7, 5)])]
        want = {r["request_id"]: _solo(cfg, params, r["prompt"],
                                       r["max_new"]) for r in reqs}
        server = serving.PagedServer(cfg, params, slots=2, page_size=16,
                                     prefill_chunk=8, mesh=mesh,
                                     longctx_ring=4)
        got = server.drain([dict(r) for r in reqs])
        assert got == want, (got, want)
        # the two long prompts rode the ring; the short one chunked
        assert server.ring_prefills == 2
        assert server.longctx_fallbacks == 0
        stats = server.page_stats()["longctx"]
        assert stats["ring"] == 4 and stats["ring_prefilled_tokens"] == 93
        assert server.ledger_violations() == []

    def test_ring_rejects_indivisible_max_seq(self):
        from dcos_commons_tpu.parallel.mesh import MeshSpec
        cfg = llama.LlamaConfig.tiny(n_layers=2, max_seq=66,
                                     attn_impl="dense")
        params = llama.init_params(cfg, jax.random.key(0))
        mesh = MeshSpec(sp=4, dp=2).build()
        with pytest.raises(ValueError, match="max_seq"):
            serving.PagedServer(cfg, params, slots=2, page_size=6,
                                prefill_chunk=6, mesh=mesh,
                                longctx_ring=4)
