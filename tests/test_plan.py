"""Plan engine tests.

Mirrors reference coverage in ``sdk/scheduler/src/test/.../plan/`` —
``DefaultPlanCoordinatorTest``, ``DeploymentStepTest``, strategy tests,
``ExponentialBackoffTest``.
"""

import pytest

from dcos_commons_tpu.plan import (CanaryStrategy, DependencyStrategy,
                                   DeploymentStep, ExponentialBackoff,
                                   ParallelStrategy, PlanCoordinator,
                                   PlanManager, PodInstanceRequirement,
                                   SerialStrategy, Status, build_deploy_plan,
                                   strategy_for)
from dcos_commons_tpu.specification import (PodInstance,
                                            load_service_yaml_str)
from dcos_commons_tpu.state import (MemPersister, StateStore, StoredTask,
                                    TaskState, TaskStatus)
from dcos_commons_tpu.specification import GoalState
from dcos_commons_tpu.utils import make_task_id

YML = """
name: svc
pods:
  hello:
    count: 2
    tasks:
      server: {goal: RUNNING, cmd: run, cpus: 0.1, memory: 32}
  world:
    count: 2
    tasks:
      server: {goal: RUNNING, cmd: run, cpus: 0.1, memory: 32}
      init: {goal: ONCE, cmd: init, cpus: 0.1, memory: 32}
"""

SPEC = load_service_yaml_str(YML, {})
TARGET = "cfg-1"


def fresh_plan(**kw):
    return build_deploy_plan(SPEC, StateStore(MemPersister()), TARGET, **kw)


def launch(step, state_store=None):
    """Simulate the matcher launching all tasks of a step; returns name->id."""
    req = step.start()
    assert req is not None
    ids = {t: make_task_id(t) for t in req.task_instance_names()}
    step.on_launch(ids)
    return ids


def run_all(step, ids, readiness=True):
    for name, tid in ids.items():
        task_spec_name = name.rsplit("-", 1)[-1]
        spec_goal = None
        state = TaskState.RUNNING
        if task_spec_name == "init":
            state = TaskState.FINISHED
        step.update_status(TaskStatus.now(tid, state, readiness_passed=readiness))


class TestDeployPlanShape:
    def test_structure(self):
        plan = fresh_plan()
        assert [p.name for p in plan.phases] == ["hello", "world"]
        assert [s.name for s in plan.phases[0].steps] == [
            "hello-0:[server]", "hello-1:[server]"]
        assert plan.status is Status.PENDING

    def test_serial_ordering(self):
        plan = fresh_plan()
        cands = plan.candidates([])
        assert [s.name for s in cands] == ["hello-0:[server]"]
        ids = launch(cands[0], None)
        assert plan.status is Status.IN_PROGRESS
        # while hello-0 is STARTING, nothing else is a candidate (serial)
        assert plan.candidates([]) == []
        run_all(cands[0], ids)
        assert cands[0].status is Status.COMPLETE
        assert [s.name for s in plan.candidates([])] == ["hello-1:[server]"]

    def test_full_deploy_to_complete(self):
        plan = fresh_plan()
        for _ in range(10):
            cands = plan.candidates([])
            if not cands:
                break
            for step in cands:
                run_all(step, launch(step))
        assert plan.status is Status.COMPLETE

    def test_dirty_assets_excluded(self):
        plan = fresh_plan()
        assert plan.candidates(["hello-0"]) == []


class TestStepStateMachine:
    def make_step(self):
        pod = SPEC.pod("world")
        req = PodInstanceRequirement(PodInstance(pod, 0), ("server", "init"))
        return DeploymentStep("world-0:[server,init]", req)

    def test_multi_task_completion(self):
        step = self.make_step()
        ids = launch(step)
        assert step.status is Status.STARTING
        step.update_status(TaskStatus.now(ids["world-0-server"], TaskState.RUNNING))
        # init not finished yet
        assert step.status is not Status.COMPLETE
        step.update_status(TaskStatus.now(ids["world-0-init"], TaskState.FINISHED))
        assert step.status is Status.COMPLETE

    def test_failure_returns_to_pending(self):
        step = self.make_step()
        ids = launch(step)
        step.update_status(TaskStatus.now(ids["world-0-server"], TaskState.FAILED))
        assert step.status is Status.PENDING

    def test_running_goal_task_exit_is_not_complete(self):
        pod = SPEC.pod("hello")
        step = DeploymentStep(
            "hello-0:[server]", PodInstanceRequirement(PodInstance(pod, 0), ("server",)))
        ids = launch(step)
        step.update_status(TaskStatus.now(ids["hello-0-server"], TaskState.FINISHED))
        assert step.status is Status.PENDING

    def test_unknown_task_id_ignored(self):
        step = self.make_step()
        launch(step)
        before = step.status
        step.update_status(TaskStatus.now(make_task_id("other-0-x"), TaskState.FAILED))
        assert step.status is before

    def test_force_complete_and_restart(self):
        step = self.make_step()
        step.force_complete()
        assert step.status is Status.COMPLETE
        step.restart()
        assert step.status is Status.PENDING


class TestReadiness:
    YML_READY = """
name: svc
pods:
  web:
    count: 1
    tasks:
      server:
        goal: RUNNING
        cmd: run
        cpus: 0.1
        memory: 32
        readiness-check: {cmd: ./ready.sh}
"""

    def test_readiness_gates_complete(self):
        spec = load_service_yaml_str(self.YML_READY, {})
        plan = build_deploy_plan(spec, StateStore(MemPersister()), TARGET)
        step = plan.steps[0]
        ids = launch(step)
        tid = ids["web-0-server"]
        step.update_status(TaskStatus.now(tid, TaskState.RUNNING, readiness_passed=False))
        assert step.status is Status.STARTED
        step.update_status(TaskStatus.now(tid, TaskState.RUNNING, readiness_passed=True))
        assert step.status is Status.COMPLETE


class TestInitialStatusFromState:
    def test_already_deployed_tasks_complete(self):
        store = StateStore(MemPersister())
        tid = make_task_id("hello-0-server")
        store.store_tasks([StoredTask(
            task_name="hello-0-server", task_id=tid, pod_type="hello", pod_index=0,
            task_spec_name="server", resource_set_id="server-resources",
            agent_id="a1", hostname="h1", target_config_id=TARGET,
            goal=GoalState.RUNNING)])
        store.store_status("hello-0-server", TaskStatus.now(tid, TaskState.RUNNING))
        plan = build_deploy_plan(SPEC, store, TARGET)
        assert plan.phases[0].steps[0].status is Status.COMPLETE
        assert plan.phases[0].steps[1].status is Status.PENDING

    def test_config_change_resets_running_tasks(self):
        store = StateStore(MemPersister())
        tid = make_task_id("hello-0-server")
        store.store_tasks([StoredTask(
            task_name="hello-0-server", task_id=tid, pod_type="hello", pod_index=0,
            task_spec_name="server", resource_set_id="server-resources",
            agent_id="a1", hostname="h1", target_config_id="old-cfg",
            goal=GoalState.RUNNING)])
        store.store_status("hello-0-server", TaskStatus.now(tid, TaskState.RUNNING))
        plan = build_deploy_plan(SPEC, store, TARGET)
        assert plan.phases[0].steps[0].status is Status.PENDING

    def test_once_task_stays_complete_across_configs(self):
        store = StateStore(MemPersister())
        tid = make_task_id("world-0-init")
        store.store_tasks([StoredTask(
            task_name="world-0-init", task_id=tid, pod_type="world", pod_index=0,
            task_spec_name="init", resource_set_id="init-resources",
            agent_id="a1", hostname="h1", target_config_id="old-cfg",
            goal=GoalState.ONCE)])
        store.store_status("world-0-init", TaskStatus.now(tid, TaskState.FINISHED))
        pod = SPEC.pod("world")
        from dcos_commons_tpu.plan import has_reached_goal_state
        assert has_reached_goal_state(store, TARGET, PodInstance(pod, 0), "init")
        assert not has_reached_goal_state(store, TARGET, PodInstance(pod, 0), "server")


class TestStrategies:
    def test_parallel(self):
        plan = fresh_plan()
        plan.phases[0].strategy = ParallelStrategy()
        cands = plan.candidates([])
        assert [s.name for s in cands] == ["hello-0:[server]", "hello-1:[server]"]

    def test_canary(self):
        plan = fresh_plan()
        phase = plan.phases[0]
        phase.strategy = CanaryStrategy()
        assert plan.candidates([]) == []
        phase.strategy.proceed()
        assert [s.name for s in plan.candidates([])] == ["hello-0:[server]"]
        run_all(phase.steps[0], launch(phase.steps[0]))
        # canary complete, but second proceed not yet given
        assert plan.candidates([]) == []
        phase.strategy.proceed()
        assert [s.name for s in plan.candidates([])] == ["hello-1:[server]"]

    def test_dependency(self):
        plan = fresh_plan()
        phase = plan.phases[0]
        phase.strategy = DependencyStrategy(
            {"hello-0:[server]": ["hello-1:[server]"]})
        cands = plan.candidates([])
        assert [s.name for s in cands] == ["hello-1:[server]"]
        run_all(phase.steps[1], launch(phase.steps[1]))
        assert [s.name for s in plan.candidates([])] == ["hello-0:[server]"]

    def test_interrupt_proceed(self):
        plan = fresh_plan()
        plan.phases[0].interrupt()
        assert plan.candidates([]) == []
        assert plan.phases[0].status is Status.WAITING
        plan.phases[0].proceed()
        assert len(plan.candidates([])) == 1

    def test_strategy_for(self):
        assert isinstance(strategy_for("serial"), SerialStrategy)
        assert isinstance(strategy_for("parallel"), ParallelStrategy)
        assert isinstance(strategy_for("canary"), CanaryStrategy)
        with pytest.raises(ValueError):
            strategy_for("bogus")


class TestCustomPlans:
    YML_PLANS = """
name: svc
pods:
  data:
    count: 2
    tasks:
      bootstrap: {goal: ONCE, cmd: b, cpus: 0.1, memory: 32}
      node: {goal: RUNNING, cmd: n, cpus: 0.1, memory: 32}
plans:
  deploy:
    strategy: serial
    phases:
      data-phase:
        pod: data
        strategy: parallel
        steps:
          - [0, [bootstrap, node]]
          - [1, [node]]
"""

    def test_yaml_plan_wins(self):
        spec = load_service_yaml_str(self.YML_PLANS, {})
        plan = build_deploy_plan(spec, StateStore(MemPersister()), TARGET)
        phase = plan.phases[0]
        assert phase.name == "data-phase"
        assert [s.name for s in phase.steps] == [
            "data-0:[bootstrap,node]", "data-1:[node]"]
        assert len(plan.candidates([])) == 2  # parallel


class TestCoordinator:
    def test_priority_and_dirty_assets(self):
        plan_a = fresh_plan()
        plan_b = fresh_plan()
        coord = PlanCoordinator([PlanManager(plan_a), PlanManager(plan_b)])
        cands = coord.get_candidates()
        # both plans want hello-0; only the first manager gets it
        assert len(cands) == 1
        assert cands[0] is plan_a.phases[0].steps[0]

    def test_in_progress_asset_blocks_other_plan(self):
        plan_a = fresh_plan()
        plan_b = fresh_plan()
        coord = PlanCoordinator([PlanManager(plan_a), PlanManager(plan_b)])
        step_a = plan_a.phases[0].steps[0]
        launch(step_a)  # hello-0 now STARTING in plan_a
        cands = coord.get_candidates()
        assert all(s.asset != "hello-0" for s in cands)


class TestBackoff:
    def test_exponential_growth_and_clear(self):
        clock = [0.0]
        b = ExponentialBackoff(initial_s=10, max_s=40, factor=2.0,
                               clock=lambda: clock[0])
        assert b.delay_remaining("t") == 0
        b.on_launch("t")
        assert b.delay_remaining("t") == pytest.approx(10)
        clock[0] = 10
        assert b.delay_remaining("t") == 0
        b.on_launch("t")
        assert b.delay_remaining("t") == pytest.approx(20)
        b.on_launch("t")
        b.on_launch("t")
        assert b.delay_remaining("t") <= 40 + 1e-9
        b.on_running("t")
        assert b.delay_remaining("t") == 0

    def test_delayed_step(self):
        clock = [0.0]
        b = ExponentialBackoff(initial_s=10, max_s=40, factor=2.0,
                               clock=lambda: clock[0])
        pod = SPEC.pod("hello")
        step = DeploymentStep(
            "hello-0:[server]",
            PodInstanceRequirement(PodInstance(pod, 0), ("server",)), backoff=b)
        ids = launch(step)
        step.update_status(TaskStatus.now(ids["hello-0-server"], TaskState.FAILED))
        assert step.status is Status.PENDING
        assert step.start() is None  # backoff active
        assert step.status is Status.DELAYED
        clock[0] = 11
        assert step.start() is not None
        assert step.status is Status.PENDING
