"""frameworks/jax — north-star service tests.

Simulation tier (reference ServiceTest.java style): every workload YAML
deploys on synthetic TPU-slice agents; the JAX distributed env contract
(JAX_COORDINATOR_ADDRESS / JAX_PROCESS_ID / JAX_NUM_PROCESSES) lands in
every task sandbox; killing one gang worker triggers a coordinated gang
re-form (SURVEY.md §7 hard part (3)).

Workload tier: the actual worker entry point runs tiny shapes on CPU —
spec-to-training end to end per BASELINE.json configs[2..4].
"""

import json
import os

import dataclasses

import pytest

from dcos_commons_tpu.state import TaskState
from dcos_commons_tpu.testing import Expect, Send, ServiceTestRunner
from dcos_commons_tpu.testing.simulation import tpu_slice_agents

from frameworks.jax import scenarios, worker


PIN = {"TPU_TOPOLOGY": "v4-32", "WORKER_COUNT": "4", "SHARD_COUNT": "4",
       "CHIPS_PER_WORKER": "4"}


def runner_for(scenario: str, env: dict | None = None,
               **kwargs) -> ServiceTestRunner:
    merged = dict(PIN)
    if env:
        merged.update(env)
    spec = scenarios.load_scenario(scenario, merged)
    kwargs.setdefault("agents", tpu_slice_agents(n=4, chips=4,
                                                 topology="v4-32"))
    return ServiceTestRunner(spec=spec, **kwargs)


def two_slice_agents(hosts_per_slice=2):
    """slice-a + slice-b agent sets for multislice gangs."""
    return (tpu_slice_agents(n=hosts_per_slice, chips=4,
                             slice_id="slice-a", topology="v4-32")
            + [dataclasses.replace(a, agent_id=f"b-{a.agent_id}",
                                   hostname=f"b-{a.hostname}")
               for a in tpu_slice_agents(n=hosts_per_slice, chips=4,
                                         slice_id="slice-b",
                                         topology="v4-32")])


class TestScenariosDeploy:
    @pytest.mark.parametrize("scenario", scenarios.list_scenarios())
    def test_deploys(self, scenario):
        kwargs = {}
        if scenario == "multislice":
            # the 2-slice gang needs two distinct slices of agents
            kwargs["agents"] = two_slice_agents()
        elif scenario == "longctx":
            # the trainer gang and the ring-prefill serving gang each
            # fill a whole v4-32 slice (4 hosts x 4 chips)
            kwargs["agents"] = two_slice_agents(hosts_per_slice=4)
        runner_for(scenario, env={"WORKER_COUNT": "4"}
                   if scenario == "multislice" else None, **kwargs).run([
            Send.until_quiet(),
            Expect.deployed(),
        ])

    def test_multislice_megascale_env(self):
        runner = runner_for("multislice", env={"WORKER_COUNT": "4"},
                            agents=two_slice_agents())
        runner.run([Send.until_quiet(), Expect.deployed()])
        launches = {l.task_name: l
                    for p in runner.cluster.launch_log for l in p.launches}
        by_slice = {}
        for name, l in launches.items():
            assert l.env["MEGASCALE_NUM_SLICES"] == "2"
            by_slice.setdefault(l.env["MEGASCALE_SLICE_ID"],
                                set()).add(l.env["TPU_SLICE_ID"])
        # two groups, each on exactly one distinct slice
        assert set(by_slice) == {"0", "1"}
        assert all(len(v) == 1 for v in by_slice.values())
        assert by_slice["0"] != by_slice["1"]

    def test_serving_endpoint_advertised(self):
        """serving.yml reserves a named `serve` port per replica; the
        scheduler's endpoints surface (EndpointQueries -> tpuctl
        endpoints serve) advertises every replica's host:port, and the
        launch env carries PORT_SERVE for the worker to bind."""
        runner = runner_for("serving", env={"SERVER_COUNT": "2"})
        runner.run([Send.until_quiet(), Expect.deployed()])
        from dcos_commons_tpu.http.queries import EndpointQueries
        eq = EndpointQueries(runner.scheduler)
        assert "serve" in eq.list()
        ep = eq.get("serve")
        assert len(ep["address"]) == 2
        assert all(":" in a for a in ep["address"])
        for plan in runner.cluster.launch_log:
            for launch in plan.launches:
                port = int(launch.env["PORT_SERVE"])
                assert port > 0

    def test_mnist_single_chip_no_gang(self):
        # configs[2]: one trainer, one chip, FINISH goal
        runner = runner_for("mnist")
        runner.run([
            Send.until_quiet(),
            Send.task_status("trainer-0-train", TaskState.FINISHED),
            Send.until_quiet(),
            Expect.deployed(),
        ])
        launches = runner.cluster.launch_log
        assert len(launches) == 1
        (launch,) = launches[0].launches
        assert launch.env["JAX_NUM_PROCESSES"] == "1"


class TestDistributedEnvContract:
    """The matcher + bootstrap export the jax.distributed bring-up contract
    (BASELINE.json north star; replaces sdk/bootstrap/main.go env export)."""

    def test_resnet_worker_env(self):
        runner = runner_for("resnet")
        runner.run([Send.until_quiet(), Expect.deployed()])
        launches = {}
        coordinator_hosts = set()
        for plan in runner.cluster.launch_log:
            for launch in plan.launches:
                launches[launch.task_name] = launch
                coordinator_hosts.add(launch.env["JAX_COORDINATOR_ADDRESS"])
        assert sorted(launches) == [
            f"worker-{i}-train" for i in range(4)]
        # one coordinator, shared by every worker
        assert len(coordinator_hosts) == 1
        ids = sorted(int(t.env["JAX_PROCESS_ID"]) for t in launches.values())
        assert ids == [0, 1, 2, 3]
        for launch in launches.values():
            assert launch.env["JAX_NUM_PROCESSES"] == "4"
            assert launch.env["POD_INSTANCE_INDEX"] in "0123"

    def test_gang_lands_on_one_slice(self):
        # two slices available; all four workers must land on one of them
        agents = (tpu_slice_agents(n=4, chips=4, slice_id="slice-a",
                                   topology="v4-32")
                  + [a for a in tpu_slice_agents(n=4, chips=4,
                                                 slice_id="slice-b",
                                                 topology="v4-32")])
        # re-id the second slice's agents to avoid collisions
        from dataclasses import replace
        agents = agents[:4] + [
            replace(a, agent_id=f"b-{i}", hostname=f"bhost-{i}")
            for i, a in enumerate(agents[4:])]
        runner = runner_for("resnet", agents=agents)
        runner.run([Send.until_quiet(), Expect.deployed()])
        slices = {p.agent.tpu.slice_id for p in runner.cluster.launch_log}
        assert len(slices) == 1


class TestGangRecovery:
    """One worker death => the failed pod is replaced AND every sibling is
    restarted in place so jax.distributed re-forms with stable ranks."""

    def test_worker_failure_restarts_gang(self):
        runner = runner_for("resnet")
        runner.run([
            Send.until_quiet(),
            Expect.deployed(),
        ])
        runner.new_launches()  # drain the deploy launches
        runner.run([
            Send.task_status("worker-2-train", TaskState.FAILED,
                             message="host died"),
            Send.until_quiet(max_cycles=100),
        ])
        relaunched = {name.rsplit("-", 1)[0] if name.endswith("-train")
                      else name for name in runner.new_launches()}
        # the whole gang relaunched, not just the failed member
        assert relaunched == {f"worker-{i}" for i in range(4)}

    def test_mnist_failure_is_solo_recovery(self):
        runner = runner_for("mnist")
        runner.run([Send.until_quiet(), Expect.deployed()])
        runner.new_launches()
        runner.run([
            Send.task_status("trainer-0-train", TaskState.FAILED),
            Send.until_quiet(max_cycles=100),
        ])
        assert set(runner.new_launches()) == {"trainer-0-train"}


class TestWorkerWorkloads:
    """Run the real task-side entry point on CPU with tiny shapes."""

    def test_mnist_trains_and_checkpoints(self, tmp_path):
        out = str(tmp_path / "ckpt")
        rc = worker.main(["mnist", "--steps", "4", "--out", out])
        assert rc == 0
        import jax
        from dcos_commons_tpu.models import mlp
        cfg = mlp.MLPConfig(in_dim=784, hidden=(512, 256), n_classes=10)
        template = mlp.init_params(cfg, jax.random.key(7))
        resumed = worker.latest_checkpoint(out, template)
        assert resumed is not None and resumed["step"] == 4

    def test_mnist_resumes_from_checkpoint(self, tmp_path, capsys):
        out = str(tmp_path / "ckpt")
        worker.main(["mnist", "--steps", "2", "--out", out])
        capsys.readouterr()
        worker.main(["mnist", "--steps", "4", "--out", out])
        events = [json.loads(line)
                  for line in capsys.readouterr().out.splitlines()]
        assert any(e.get("event") == "resumed" and e["step"] == 2
                   for e in events)

    def test_resnet_dp_step(self, tmp_path, capsys):
        out = str(tmp_path / "ckpt")
        rc = worker.main(["resnet", "--steps", "1", "--batch", "8",
                          "--depth", "18", "--out", out])
        assert rc == 0
        events = [json.loads(line)
                  for line in capsys.readouterr().out.splitlines()]
        done = [e for e in events if e.get("event") == "done"]
        assert done and done[0]["images_per_sec_per_chip"] > 0

    def test_llama_train_ring_on_cpu_mesh(self, tmp_path, capsys):
        # long-context workload on the 8-device virtual CPU mesh: ring
        # attention over sp, single process (the gang path is simulated in
        # TestScenariosDeploy via longctx.yml)
        out = str(tmp_path / "ckpt")
        rc = worker.main(["llama-train", "--steps", "1", "--seq", "64",
                          "--attn", "ring", "--sp", "2", "--tp", "2",
                          "--out", out])
        assert rc == 0
        events = [json.loads(line)
                  for line in capsys.readouterr().out.splitlines()]
        done = [e for e in events if e.get("event") == "done"]
        assert done and done[0]["attn"] == "ring"
        assert done[0]["mesh"] == {"dp": 2, "sp": 2, "tp": 2,
                                   "ring_layout": "contiguous"}
        assert done[0]["tokens_per_sec"] > 0

    def test_llama_train_ring_zigzag(self, tmp_path, capsys):
        # the balanced causal layout end to end through the worker;
        # seq 64 % (2*sp=4) == 0 so zigzag engages
        rc = worker.main(["llama-train", "--steps", "1", "--seq", "64",
                          "--attn", "ring", "--ring-layout", "zigzag",
                          "--sp", "2", "--out", str(tmp_path / "ckpt")])
        assert rc == 0
        events = [json.loads(line)
                  for line in capsys.readouterr().out.splitlines()]
        done = [e for e in events if e.get("event") == "done"]
        assert done and done[0]["mesh"]["ring_layout"] == "zigzag"
        import math
        assert math.isfinite(done[0]["final_loss"])

    def test_llama_shard_serves(self, tmp_path, capsys, monkeypatch):
        monkeypatch.chdir(tmp_path)
        rc = worker.main(["llama", "--preset", "tiny", "--gen-len", "4"])
        assert rc == 0
        assert os.path.exists("serving.ready")
        events = [json.loads(line)
                  for line in capsys.readouterr().out.splitlines()]
        done = [e for e in events if e.get("event") == "done"]
        assert done and done[0]["tokens_per_sec"] > 0

    def test_llama_serving_http_front_door(self, tmp_path):
        """The serving.yml path, traffic included: --serve --slots runs
        the continuous-batching engine behind the HTTP ingress; a real
        client POSTs a prompt to the advertised port and gets tokens +
        latency timings back; heartbeats report the ingress stats; the
        readiness probe (frameworks/jax/probe.py) passes. Driven as the
        real process the scheduler would launch."""
        import subprocess
        import sys
        import time as _time
        import urllib.request

        # single device: the conftest's 8-device XLA_FLAGS would leak in
        # and shard the mesh, which falls back to heartbeat decode
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=1",
                   PYTHONPATH=os.path.abspath(
                       os.path.join(os.path.dirname(__file__), "..")))
        proc = subprocess.Popen(
            [sys.executable, "-m", "frameworks.jax.worker", "llama",
             "--serve", "--slots", "2", "--serve-interval", "0.1",
             "--gen-len", "4"],
            cwd=tmp_path, env=env, stdout=subprocess.PIPE, text=True)
        try:
            import queue
            import threading

            lines: queue.Queue = queue.Queue()

            def pump():
                for raw in proc.stdout:
                    lines.put(raw)

            # reader thread so the deadline is real: a blocked
            # readline() would otherwise hang the suite past it
            threading.Thread(target=pump, daemon=True).start()

            def next_event(deadline):
                while _time.time() < deadline:
                    try:
                        return json.loads(lines.get(timeout=min(
                            5.0, max(deadline - _time.time(), 0.1))))
                    except queue.Empty:
                        continue
                return None

            deadline = _time.time() + 120
            serving = None
            while (e := next_event(deadline)) is not None:
                if e.get("event") == "serving":
                    serving = e
                    break
            assert serving and serving["slots"] == 2, serving
            port = serving["port"]
            assert port > 0
            # the re-stamped readiness marker carries the bound port
            assert (tmp_path / "serving.ready").read_text().split()[1] \
                == str(port)

            # the readiness probe the yml runs — against this very worker
            probe = subprocess.run(
                [sys.executable, "-m", "frameworks.jax.probe"],
                env=dict(env, PORT_SERVE=str(port)),
                capture_output=True, text=True)
            assert probe.returncode == 0, probe.stderr

            # real traffic through the front door
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/generate",
                data=json.dumps({"prompt": [1, 2, 3, 4],
                                 "max_new": 5}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                body = json.loads(r.read())
            assert r.status == 200
            assert len(body["tokens"]) == 5
            assert body["ttft_ms"] > 0 and body["tpot_ms"] > 0

            # heartbeats now carry the ingress stats
            deadline = _time.time() + 60
            heartbeat = None
            while (e := next_event(deadline)) is not None:
                if e.get("event") == "heartbeat" \
                        and e.get("requests", 0) >= 1:
                    heartbeat = e
                    break
            assert heartbeat, "no post-request heartbeat before deadline"
            assert heartbeat["tokens"] >= 5
            assert heartbeat["ttft_ms"]["p50"] > 0
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


class TestPipelineParallel:
    def test_llama_train_pp_on_cpu_mesh(self, tmp_path, capsys):
        rc = worker.main(["llama-train", "--steps", "1", "--seq", "64",
                          "--pp", "2", "--out", str(tmp_path / "ckpt")])
        assert rc == 0
        events = [json.loads(line)
                  for line in capsys.readouterr().out.splitlines()]
        done = [e for e in events if e.get("event") == "done"]
        assert done and done[0]["mesh"]["pp"] == 2
        import math
        assert math.isfinite(done[0]["final_loss"])

    def test_pipelined_forward_matches_dense(self):
        import numpy as np
        from jax.sharding import Mesh
        from dcos_commons_tpu.models import llama
        import jax
        import jax.numpy as jnp
        cfg = llama.LlamaConfig.tiny(n_layers=4, attn_impl="dense",
                                     dtype=jnp.float32)
        params = llama.init_params(cfg, jax.random.key(0))
        tokens = jax.random.randint(jax.random.key(1), (4, 32), 0,
                                    cfg.vocab_size)
        with jax.default_matmul_precision("highest"):
            ref = llama.forward(cfg, params, tokens)
            mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("pp",))
            out = llama.forward_pipelined(
                cfg, llama.stack_pipeline_params(params, 2), tokens, mesh,
                n_micro=2)
        assert float(jnp.abs(ref - out).max()) < 1e-5


class TestExpertParallel:
    def test_llama_train_moe_on_cpu_mesh(self, tmp_path, capsys):
        rc = worker.main(["llama-train", "--steps", "1", "--seq", "64",
                          "--ep", "4", "--out", str(tmp_path / "ckpt")])
        assert rc == 0
        events = [json.loads(line)
                  for line in capsys.readouterr().out.splitlines()]
        done = [e for e in events if e.get("event") == "done"]
        assert done and done[0]["mesh"]["ep"] == 4
        import math
        assert math.isfinite(done[0]["final_loss"])

    def test_moe_expert_grads_flow(self):
        import jax
        import jax.numpy as jnp
        from dcos_commons_tpu.models import llama
        from dcos_commons_tpu.parallel.mesh import MeshSpec
        from dcos_commons_tpu.parallel.moe import MoEConfig
        cfg = llama.LlamaConfig.tiny(n_layers=2)
        mesh = MeshSpec(ep=4, dp=2).build()
        mcfg = MoEConfig(num_experts=4)
        params = llama.init_moe_params(cfg, 4, jax.random.key(0))
        toks = jax.random.randint(jax.random.key(1), (2, 33), 0,
                                  cfg.vocab_size)
        with mesh:
            loss, _ = llama.loss_fn_moe(cfg, params, toks, mesh, mcfg)
            assert bool(jnp.isfinite(loss))
            g = jax.grad(lambda p: llama.loss_fn_moe(
                cfg, p, toks, mesh, mcfg)[0])(params)
        assert float(jnp.abs(g["layers"]["w_in"]).max()) > 0
        assert float(jnp.abs(g["layers"]["router"]).max()) > 0


class TestMultisliceRecovery:
    def test_member_failure_reforms_gang_and_keeps_slice_groups(self):
        runner = runner_for("multislice", env={"WORKER_COUNT": "4"},
                            agents=two_slice_agents())
        runner.run([Send.until_quiet(), Expect.deployed()])
        before = {}
        for p in runner.cluster.launch_log:
            for l in p.launches:
                before[l.task_name] = l.env["TPU_SLICE_ID"]
        n_deploy_plans = len(runner.cluster.launch_log)
        runner.run([
            Send.task_status("worker-3-train", TaskState.FAILED),
            Send.until_quiet(),
        ])
        # gang re-form relaunched every member...
        after = {}
        for p in runner.cluster.launch_log[n_deploy_plans:]:
            for l in p.launches:
                after[l.task_name] = l.env["TPU_SLICE_ID"]
        assert set(after) == set(before), (before, after)
        # ...with the same group-to-slice assignment (stable MEGASCALE ids)
        assert after == before
        from dcos_commons_tpu.plan import Status
        assert runner.scheduler.plan("recovery").status is Status.COMPLETE


class TestProfilerHooks:
    """SURVEY §5: jax profiler + XLA dump hooks in the workload layer."""

    def test_profile_dir_writes_a_trace(self, tmp_path, capsys):
        prof = tmp_path / "prof"
        rc = worker.main(["mnist", "--steps", "2",
                          "--profile-dir", str(prof)])
        assert rc == 0
        events = [json.loads(line)
                  for line in capsys.readouterr().out.splitlines()]
        assert any(e.get("event") == "profiling" for e in events)
        traces = list(prof.rglob("*.xplane.pb")) \
            + list(prof.rglob("*.trace.json.gz"))
        assert traces, f"no trace files under {prof}"

    def test_profile_dir_via_env(self, tmp_path, capsys, monkeypatch):
        prof = tmp_path / "prof-env"
        monkeypatch.setenv("TPU_PROFILE_DIR", str(prof))
        rc = worker.main(["mnist", "--steps", "1"])
        assert rc == 0
        assert list(prof.rglob("*.xplane.pb")) \
            or list(prof.rglob("*.trace.json.gz"))

    def test_xla_dump_via_launch_env(self, tmp_path):
        # XLA_FLAGS must precede the task interpreter's backend init, so
        # the SCHEDULER injects it into the launch env from
        # TPU_XLA_DUMP_DIR (evaluator._build_launch); here we run the
        # worker exactly as the agent would exec it, with that env
        import subprocess
        import sys
        dump = tmp_path / "xla-dump"
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=8 "
                             f"--xla_dump_to={dump}")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, "-m", "frameworks.jax.worker", "mnist",
             "--steps", "1"], cwd=repo, env=env, capture_output=True,
            text=True, timeout=300)
        assert r.returncode == 0, r.stdout + r.stderr
        assert dump.exists() and any(dump.iterdir()), \
            f"no XLA dump artifacts under {dump}"

    def test_scheduler_injects_xla_flags_from_dump_env(self):
        # the spec-env half: TPU_XLA_DUMP_DIR in task env becomes
        # XLA_FLAGS in the launch command
        from dcos_commons_tpu.matching import (Evaluator,
                                               ReservationLedger)
        from dcos_commons_tpu.plan import PodInstanceRequirement
        from dcos_commons_tpu.specification import (PodInstance,
                                                    load_service_yaml_str)
        from dcos_commons_tpu.testing.simulation import default_agents
        yml = """
name: svc
pods:
  trainer:
    count: 1
    tasks:
      train:
        goal: RUNNING
        cmd: python -m frameworks.jax.worker mnist
        cpus: 0.5
        memory: 128
        env: {TPU_XLA_DUMP_DIR: /mnt/dumps}
"""
        spec = load_service_yaml_str(yml, {})
        pod = spec.pod("trainer")
        req = PodInstanceRequirement(PodInstance(pod, 0), ("train",))
        plan, _ = Evaluator("svc").evaluate(req, default_agents(1), [],
                                            ReservationLedger())
        env = plan.launches[0].env
        assert env["XLA_FLAGS"] == "--xla_dump_to=/mnt/dumps"
