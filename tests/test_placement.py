"""Placement DSL tests (reference ``offer/evaluate/placement/*Test`` coverage)."""


from dcos_commons_tpu.agent import AgentInfo, TaskRecord, TpuInventory
from dcos_commons_tpu.matching import (AndRule, HostnameRule,
                                       MaxPerAttributeRule, MaxPerHostnameRule,
                                       MaxPerZoneRule, NotRule, OrRule,
                                       RoundRobinByAttributeRule,
                                       RoundRobinByHostnameRule, StringMatcher,
                                       TaskTypeRule, TpuSliceRule, ZoneRule,
                                       parse_marathon_constraints, rule_from_json,
                                       rule_to_json)


def agent(i, zone=None, tpu=TpuInventory(), attrs=None):
    return AgentInfo(agent_id=f"a{i}", hostname=f"host{i}", cpus=8, memory_mb=32768,
                     tpu=tpu, zone=zone, region="us-central1",
                     attributes=attrs or {})


def task(pod_type, idx, agent_info):
    return TaskRecord(task_name=f"{pod_type}-{idx}-server", pod_type=pod_type,
                      pod_index=idx, agent_id=agent_info.agent_id,
                      hostname=agent_info.hostname, zone=agent_info.zone,
                      region=agent_info.region,
                      attributes=dict(agent_info.attributes))


def test_hostname_rule():
    r = HostnameRule(StringMatcher.exact("host1"))
    assert r.filter(agent(1), "hello-0", []).passes
    assert not r.filter(agent(2), "hello-0", []).passes


def test_combinators():
    r = AndRule((HostnameRule(StringMatcher.glob("host*")),
                 NotRule(HostnameRule(StringMatcher.exact("host2")))))
    assert r.filter(agent(1), "p-0", []).passes
    assert not r.filter(agent(2), "p-0", []).passes
    r2 = OrRule((HostnameRule(StringMatcher.exact("hostX")),
                 ZoneRule(StringMatcher.exact("z1"))))
    assert r2.filter(agent(1, zone="z1"), "p-0", []).passes
    assert not r2.filter(agent(1, zone="z2"), "p-0", []).passes


def test_max_per_hostname():
    r = MaxPerHostnameRule(max_count=1)
    a1, a2 = agent(1), agent(2)
    tasks = [task("hello", 0, a1)]
    assert not r.filter(a1, "hello-1", tasks).passes
    assert r.filter(a2, "hello-1", tasks).passes
    # replacing the same pod instance doesn't veto itself
    assert r.filter(a1, "hello-0", tasks).passes
    # other pod types don't count
    assert r.filter(a1, "world-0", tasks).passes


def test_max_per_zone():
    r = MaxPerZoneRule(max_count=2)
    a1, a2, a3 = agent(1, "z1"), agent(2, "z1"), agent(3, "z2")
    tasks = [task("c", 0, a1), task("c", 1, a2)]
    assert not r.filter(a1, "c-2", tasks).passes
    assert r.filter(a3, "c-2", tasks).passes


def test_round_robin_hostname():
    r = RoundRobinByHostnameRule(group_count=3)
    a1, a2, a3 = agent(1), agent(2), agent(3)
    assert r.filter(a1, "p-0", []).passes
    tasks = [task("p", 0, a1)]
    # host1 now above the floor while unseen hosts remain
    assert not r.filter(a1, "p-1", tasks).passes
    assert r.filter(a2, "p-1", tasks).passes
    tasks.append(task("p", 1, a2))
    assert r.filter(a3, "p-2", tasks).passes
    tasks.append(task("p", 2, a3))
    # all groups seen, floor is 1 -> host1 admissible again
    assert r.filter(a1, "p-3", tasks).passes


def test_round_robin_attribute():
    """Reference RoundRobinByAttributeRule: spread over distinct attribute
    values (two agents can share a rack — counting is per value, not per
    agent)."""
    r = RoundRobinByAttributeRule(attribute="rack", group_count=2)
    a1 = agent(1, attrs={"rack": "r1"})
    a2 = agent(2, attrs={"rack": "r1"})   # same rack, different host
    a3 = agent(3, attrs={"rack": "r2"})
    no_attr = agent(4)
    assert r.filter(a1, "p-0", []).passes
    assert not r.filter(no_attr, "p-0", []).passes
    tasks = [task("p", 0, a1)]
    # rack r1 above floor while rack r2 untouched — even on the OTHER r1 host
    assert not r.filter(a1, "p-1", tasks).passes
    assert not r.filter(a2, "p-1", tasks).passes
    assert r.filter(a3, "p-1", tasks).passes
    tasks.append(task("p", 1, a3))
    # both racks seen at 1 -> floor 1, r1 admissible again
    assert r.filter(a2, "p-2", tasks).passes
    # replacing a pod doesn't count itself
    assert r.filter(a1, "p-0", tasks).passes


def test_round_robin_attribute_json_roundtrip():
    r = RoundRobinByAttributeRule(attribute="rack", group_count=3)
    assert rule_from_json(rule_to_json(r)) == r
    r2 = parse_marathon_constraints('[["rack", "GROUP_BY", "3"]]')
    assert r2 == r


def test_max_per_attribute_counts_by_value():
    """Two hosts in one rack share the rack's budget (launch-time task
    attributes, not same-agent approximation)."""
    r = MaxPerAttributeRule(max_count=1, attribute="rack")
    a1 = agent(1, attrs={"rack": "r1"})
    a2 = agent(2, attrs={"rack": "r1"})
    a3 = agent(3, attrs={"rack": "r2"})
    tasks = [task("p", 0, a1)]
    assert not r.filter(a2, "p-1", tasks).passes
    assert r.filter(a3, "p-1", tasks).passes
    # legacy records without attributes fall back to same-agent counting
    legacy = TaskRecord(task_name="p-0-server", pod_type="p", pod_index=0,
                        agent_id=a1.agent_id, hostname=a1.hostname)
    assert r.filter(a2, "p-1", [legacy]).passes
    assert not r.filter(a1, "p-1", [legacy]).passes
    # a record with OTHER attributes but not this one also falls back to
    # same-agent counting (an agent relabelled after launch must not open
    # the cap on its own host)
    other_attr = TaskRecord(task_name="p-0-server", pod_type="p", pod_index=0,
                            agent_id=a1.agent_id, hostname=a1.hostname,
                            attributes={"foo": "x"})
    assert not r.filter(a1, "p-1", [other_attr]).passes
    assert r.filter(a3, "p-1", [other_attr]).passes


def test_task_type_rules():
    a1, a2 = agent(1), agent(2)
    tasks = [task("seed", 0, a1)]
    colocate = TaskTypeRule("seed", "colocate")
    avoid = TaskTypeRule("seed", "avoid")
    assert colocate.filter(a1, "node-0", tasks).passes
    assert not colocate.filter(a2, "node-0", tasks).passes
    assert not avoid.filter(a1, "node-0", tasks).passes
    assert avoid.filter(a2, "node-0", tasks).passes


def test_tpu_slice_rule():
    r = TpuSliceRule(topology="v4-32")
    on_slice = agent(1, tpu=TpuInventory(chips=4, slice_id="s0", topology="v4-32"))
    off_slice = agent(2)
    wrong_topo = agent(3, tpu=TpuInventory(chips=4, slice_id="s1", topology="v4-16"))
    assert r.filter(on_slice, "w-0", []).passes
    assert not r.filter(off_slice, "w-0", []).passes
    assert not r.filter(wrong_topo, "w-0", []).passes


def test_marathon_constraints():
    r = parse_marathon_constraints('[["hostname", "UNIQUE"]]')
    assert isinstance(r, MaxPerHostnameRule) and r.max_count == 1
    r = parse_marathon_constraints('hostname:UNIQUE')
    assert isinstance(r, MaxPerHostnameRule)
    r = parse_marathon_constraints('[["zone", "GROUP_BY", "3"]]')
    assert r.type == "round-robin-zone"
    r = parse_marathon_constraints('[["hostname", "CLUSTER", "host7"], ["zone", "MAX_PER", "2"]]')
    assert isinstance(r, AndRule)
    assert r.filter(agent(7, zone="z1"), "p-0", []).passes
    assert not r.filter(agent(8, zone="z1"), "p-0", []).passes
    r = parse_marathon_constraints('[["hostname", "LIKE", "host[12]"]]')
    assert r.filter(agent(1), "p-0", []).passes
    assert not r.filter(agent(3), "p-0", []).passes
    r = parse_marathon_constraints('[["hostname", "UNLIKE", "host1"]]')
    assert not r.filter(agent(1), "p-0", []).passes


def test_json_round_trip():
    rules = [
        AndRule((HostnameRule(StringMatcher.regex("h.*")),
                 OrRule((MaxPerZoneRule(2), NotRule(TaskTypeRule("x", "avoid")))))),
        TpuSliceRule(slice_id="s0", topology="4x4x4"),
        RoundRobinByHostnameRule(group_count=5),
        parse_marathon_constraints('[["hostname", "UNIQUE"]]'),
    ]
    for r in rules:
        back = rule_from_json(rule_to_json(r))
        assert back == r, r
