"""Placement DSL tests (reference ``offer/evaluate/placement/*Test`` coverage)."""

import pytest

from dcos_commons_tpu.agent import AgentInfo, TaskRecord, TpuInventory
from dcos_commons_tpu.matching import (AndRule, HostnameRule, MaxPerHostnameRule,
                                       MaxPerZoneRule, NotRule, OrRule,
                                       RoundRobinByHostnameRule, StringMatcher,
                                       TaskTypeRule, TpuSliceRule, ZoneRule,
                                       parse_marathon_constraints, rule_from_json,
                                       rule_to_json)


def agent(i, zone=None, tpu=TpuInventory(), attrs=None):
    return AgentInfo(agent_id=f"a{i}", hostname=f"host{i}", cpus=8, memory_mb=32768,
                     tpu=tpu, zone=zone, region="us-central1",
                     attributes=attrs or {})


def task(pod_type, idx, agent_info):
    return TaskRecord(task_name=f"{pod_type}-{idx}-server", pod_type=pod_type,
                      pod_index=idx, agent_id=agent_info.agent_id,
                      hostname=agent_info.hostname, zone=agent_info.zone,
                      region=agent_info.region)


def test_hostname_rule():
    r = HostnameRule(StringMatcher.exact("host1"))
    assert r.filter(agent(1), "hello-0", []).passes
    assert not r.filter(agent(2), "hello-0", []).passes


def test_combinators():
    r = AndRule((HostnameRule(StringMatcher.glob("host*")),
                 NotRule(HostnameRule(StringMatcher.exact("host2")))))
    assert r.filter(agent(1), "p-0", []).passes
    assert not r.filter(agent(2), "p-0", []).passes
    r2 = OrRule((HostnameRule(StringMatcher.exact("hostX")),
                 ZoneRule(StringMatcher.exact("z1"))))
    assert r2.filter(agent(1, zone="z1"), "p-0", []).passes
    assert not r2.filter(agent(1, zone="z2"), "p-0", []).passes


def test_max_per_hostname():
    r = MaxPerHostnameRule(max_count=1)
    a1, a2 = agent(1), agent(2)
    tasks = [task("hello", 0, a1)]
    assert not r.filter(a1, "hello-1", tasks).passes
    assert r.filter(a2, "hello-1", tasks).passes
    # replacing the same pod instance doesn't veto itself
    assert r.filter(a1, "hello-0", tasks).passes
    # other pod types don't count
    assert r.filter(a1, "world-0", tasks).passes


def test_max_per_zone():
    r = MaxPerZoneRule(max_count=2)
    a1, a2, a3 = agent(1, "z1"), agent(2, "z1"), agent(3, "z2")
    tasks = [task("c", 0, a1), task("c", 1, a2)]
    assert not r.filter(a1, "c-2", tasks).passes
    assert r.filter(a3, "c-2", tasks).passes


def test_round_robin_hostname():
    r = RoundRobinByHostnameRule(group_count=3)
    a1, a2, a3 = agent(1), agent(2), agent(3)
    assert r.filter(a1, "p-0", []).passes
    tasks = [task("p", 0, a1)]
    # host1 now above the floor while unseen hosts remain
    assert not r.filter(a1, "p-1", tasks).passes
    assert r.filter(a2, "p-1", tasks).passes
    tasks.append(task("p", 1, a2))
    assert r.filter(a3, "p-2", tasks).passes
    tasks.append(task("p", 2, a3))
    # all groups seen, floor is 1 -> host1 admissible again
    assert r.filter(a1, "p-3", tasks).passes


def test_task_type_rules():
    a1, a2 = agent(1), agent(2)
    tasks = [task("seed", 0, a1)]
    colocate = TaskTypeRule("seed", "colocate")
    avoid = TaskTypeRule("seed", "avoid")
    assert colocate.filter(a1, "node-0", tasks).passes
    assert not colocate.filter(a2, "node-0", tasks).passes
    assert not avoid.filter(a1, "node-0", tasks).passes
    assert avoid.filter(a2, "node-0", tasks).passes


def test_tpu_slice_rule():
    r = TpuSliceRule(topology="v4-32")
    on_slice = agent(1, tpu=TpuInventory(chips=4, slice_id="s0", topology="v4-32"))
    off_slice = agent(2)
    wrong_topo = agent(3, tpu=TpuInventory(chips=4, slice_id="s1", topology="v4-16"))
    assert r.filter(on_slice, "w-0", []).passes
    assert not r.filter(off_slice, "w-0", []).passes
    assert not r.filter(wrong_topo, "w-0", []).passes


def test_marathon_constraints():
    r = parse_marathon_constraints('[["hostname", "UNIQUE"]]')
    assert isinstance(r, MaxPerHostnameRule) and r.max_count == 1
    r = parse_marathon_constraints('hostname:UNIQUE')
    assert isinstance(r, MaxPerHostnameRule)
    r = parse_marathon_constraints('[["zone", "GROUP_BY", "3"]]')
    assert r.type == "round-robin-zone"
    r = parse_marathon_constraints('[["hostname", "CLUSTER", "host7"], ["zone", "MAX_PER", "2"]]')
    assert isinstance(r, AndRule)
    assert r.filter(agent(7, zone="z1"), "p-0", []).passes
    assert not r.filter(agent(8, zone="z1"), "p-0", []).passes
    r = parse_marathon_constraints('[["hostname", "LIKE", "host[12]"]]')
    assert r.filter(agent(1), "p-0", []).passes
    assert not r.filter(agent(3), "p-0", []).passes
    r = parse_marathon_constraints('[["hostname", "UNLIKE", "host1"]]')
    assert not r.filter(agent(1), "p-0", []).passes


def test_json_round_trip():
    rules = [
        AndRule((HostnameRule(StringMatcher.regex("h.*")),
                 OrRule((MaxPerZoneRule(2), NotRule(TaskTypeRule("x", "avoid")))))),
        TpuSliceRule(slice_id="s0", topology="4x4x4"),
        RoundRobinByHostnameRule(group_count=5),
        parse_marathon_constraints('[["hostname", "UNIQUE"]]'),
    ]
    for r in rules:
        back = rule_from_json(rule_to_json(r))
        assert back == r, r
