"""The annotation-consistency gate (``tools/type_check.py``) must flag
seeded type errors and stay at zero findings on idiomatic code — it is a
hard CI gate, so both directions matter."""

import textwrap

from tools import type_check as tc


def run_on(tmp_path, **files):
    """Write a mini-project and run the checker on it."""
    paths = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        paths.append(p)
    # re-root the checker at the tmp project
    old_repo = tc.REPO
    tc.REPO = tmp_path
    try:
        import ast
        modules = {}
        sources = {}
        for f in tc._iter_py_files([str(tmp_path)]):
            source = f.read_text()
            info = tc._index_module(f, ast.parse(source))
            modules[info.name] = info
            sources[info.name] = source
        project = tc.Project(modules)
        findings = []
        for info in modules.values():
            noqa = tc._noqa_lines(sources[info.name])
            tc._check_typed_attrs(info, project, noqa, findings)
            tc._check_calls(info, project, noqa, findings)
        return findings
    finally:
        tc.REPO = old_repo


LIB = """
    from dataclasses import dataclass

    @dataclass(frozen=True)
    class Task:
        name: str
        index: int
        zone: str = "z1"

    class Store:
        def __init__(self, root: str, cache: bool = False):
            self.root = root
            self._items = {}

        def fetch(self, key: str):
            return self._items.get(key)

    def launch(task: Task, retries: int = 3) -> str:
        return task.name * retries
"""


def test_clean_project_has_no_findings(tmp_path):
    findings = run_on(
        tmp_path, **{
            "lib.py": LIB,
            "app.py": """
            from lib import Store, Task, launch

            def go(t: Task):
                s = Store("/tmp", cache=True)
                s.fetch(t.name)
                return launch(t, retries=2), t.index, t.zone
            """,
        })
    assert findings == []


def test_attr_typo_on_annotated_param(tmp_path):
    findings = run_on(
        tmp_path, **{
            "lib.py": LIB,
            "app.py": """
            from lib import Task

            def go(t: Task):
                return t.nam
            """,
        })
    assert len(findings) == 1 and findings[0].code == "T2"
    assert "nam" in findings[0].message


def test_attr_typo_on_ctor_local(tmp_path):
    findings = run_on(
        tmp_path, **{
            "lib.py": LIB,
            "app.py": """
            from lib import Store

            def go():
                s = Store("/tmp")
                return s.fetchh("k")
            """,
        })
    assert [f.code for f in findings] == ["T2"]


def test_reassigned_local_not_pinned(tmp_path):
    findings = run_on(
        tmp_path, **{
            "lib.py": LIB,
            "app.py": """
            from lib import Store

            def go(other):
                s = Store("/tmp")
                s = other
                return s.anything_goes
            """,
        })
    assert findings == []


def test_cross_module_unknown_kwarg(tmp_path):
    findings = run_on(
        tmp_path, **{
            "lib.py": LIB,
            "app.py": """
            from lib import Task, launch

            def go(t: Task):
                return launch(t, retriez=2)
            """,
        })
    assert [f.code for f in findings] == ["T3"]
    assert "retriez" in findings[0].message


def test_ctor_unknown_kwarg_and_missing_required(tmp_path):
    findings = run_on(
        tmp_path, **{
            "lib.py": LIB,
            "app.py": """
            from lib import Store, Task

            def go():
                Store("/tmp", bogus=1)
                Task(name="x")          # missing required 'index'
            """,
        })
    codes = sorted(f.code for f in findings)
    assert codes == ["T3", "T3"]
    assert any("bogus" in f.message for f in findings)
    assert any("index" in f.message for f in findings)


def test_dataclass_ctor_ok(tmp_path):
    findings = run_on(
        tmp_path, **{
            "lib.py": LIB,
            "app.py": """
            from lib import Task

            def go():
                return Task("a", 1), Task(name="b", index=2, zone="z9")
            """,
        })
    assert findings == []


def test_literal_type_mismatch(tmp_path):
    findings = run_on(
        tmp_path, **{
            "lib.py": LIB,
            "app.py": """
            from lib import Task, launch

            def go(t: Task):
                return launch(t, retries="three")
            """,
        })
    assert [f.code for f in findings] == ["T4"]


def test_module_attr_call_checked(tmp_path):
    findings = run_on(
        tmp_path, **{
            "pkg/__init__.py": "",
            "pkg/lib.py": LIB,
            "app.py": """
            def go():
                from pkg import lib
                return lib.launch(1, 2, 3)   # max 2 positionals
            """,
        })
    assert [f.code for f in findings] == ["T3"]


def test_noqa_suppresses(tmp_path):
    findings = run_on(
        tmp_path, **{
            "lib.py": LIB,
            "app.py": """
            from lib import Task

            def go(t: Task):
                return t.nam  # noqa: duck-typed caller
            """,
        })
    assert findings == []


def test_unknown_base_class_skipped(tmp_path):
    findings = run_on(
        tmp_path, **{
            "lib.py": """
            import threading

            class Worker(threading.Thread):
                def __init__(self):
                    super().__init__()
                    self.jobs = 0
            """,
            "app.py": """
            from lib import Worker

            def go():
                w = Worker()
                return w.daemon  # Thread attr: surface unresolvable, skip
            """,
        })
    assert findings == []


def test_tree_is_clean():
    """The repo itself must stay at zero findings (CI hard gate)."""
    import subprocess
    import sys
    r = subprocess.run([sys.executable, "-m", "tools.type_check"],
                       capture_output=True, text=True, cwd=str(tc.REPO))
    assert r.returncode == 0, r.stdout
