"""Decommission + uninstall tests (reference
``scheduler/decommission/DecommissionPlanFactoryTest``,
``frameworks/helloworld/.../ServiceTest.java:374`` decommission scenario,
``uninstall/UninstallSchedulerTest``)."""

from dcos_commons_tpu.agent import AgentInfo, FakeCluster, PortRange
from dcos_commons_tpu.plan import Status
from dcos_commons_tpu.scheduler import ServiceScheduler
from dcos_commons_tpu.specification import load_service_yaml_str
from dcos_commons_tpu.state import MemPersister

YML3 = """
name: svc
pods:
  node:
    count: 3
    allow-decommission: true
    tasks:
      server: {goal: RUNNING, cmd: run, cpus: 0.5, memory: 256}
"""

YML2 = YML3.replace("count: 3", "count: 2")


def agents(n=3):
    return [AgentInfo(agent_id=f"a{i}", hostname=f"h{i}", cpus=4,
                      memory_mb=8192, disk_mb=8192,
                      ports=(PortRange(10000, 10100),)) for i in range(n)]


def test_scale_down_decommissions_highest_index():
    persister = MemPersister()
    cluster = FakeCluster(agents())
    sched = ServiceScheduler(load_service_yaml_str(YML3, {}), persister, cluster)
    sched.run_until_quiet()
    assert len(sched.state.fetch_tasks()) == 3
    reservations_before = len(sched.ledger.all())

    sched2 = ServiceScheduler(load_service_yaml_str(YML2, {}), persister, cluster)
    sched2.run_until_quiet()
    # node-2 torn down: killed, unreserved, erased
    names = {t.task_name for t in sched2.state.fetch_tasks()}
    assert names == {"node-0-server", "node-1-server"}
    assert len(sched2.ledger.all()) == reservations_before - 1
    assert not any(r.pod_instance_name == "node-2"
                   for r in sched2.ledger.all())
    decommission = sched2.plan("decommission")
    assert decommission.status is Status.COMPLETE
    assert any("node-2" in tid for tid in cluster.kill_log)
    # deploy plan unaffected
    assert sched2.plan("deploy").status is Status.COMPLETE


def test_scale_down_without_allow_decommission_rejected():
    yml_locked = YML3.replace("allow-decommission: true",
                              "allow-decommission: false")
    persister = MemPersister()
    cluster = FakeCluster(agents())
    sched = ServiceScheduler(load_service_yaml_str(yml_locked, {}), persister, cluster)
    sched.run_until_quiet()
    shrunk = yml_locked.replace("count: 3", "count: 2")
    sched2 = ServiceScheduler(load_service_yaml_str(shrunk, {}), persister, cluster)
    assert sched2.config_errors
    sched2.run_until_quiet()
    assert len(sched2.state.fetch_tasks()) == 3  # nothing torn down


def test_uninstall_tears_everything_down():
    persister = MemPersister()
    cluster = FakeCluster(agents())
    sched = ServiceScheduler(load_service_yaml_str(YML3, {}), persister, cluster)
    sched.run_until_quiet()
    assert len(cluster.launch_log) == 3

    sched_un = ServiceScheduler(load_service_yaml_str(YML3, {}), persister,
                                cluster, uninstall=True)
    sched_un.run_until_quiet()
    assert sched_un.uninstall_complete
    assert sched_un.state.fetch_tasks() == []
    assert sched_un.ledger.all() == [] or all(
        False for _ in sched_un.reservation_store.load_ledger().all())
    assert len(cluster.kill_log) == 3
    # no tasks left running on any agent
    for agent in cluster.agents():
        assert cluster.running_task_ids(agent.agent_id) == []


def test_uninstall_plan_shape():
    persister = MemPersister()
    cluster = FakeCluster(agents())
    sched = ServiceScheduler(load_service_yaml_str(YML3, {}), persister, cluster)
    sched.run_until_quiet()
    sched_un = ServiceScheduler(load_service_yaml_str(YML3, {}), persister,
                                cluster, uninstall=True)
    plan = sched_un.plan("uninstall")
    assert [p.name for p in plan.phases] == [
        "uninstall-node-0", "uninstall-node-1", "uninstall-node-2", "deregister"]
