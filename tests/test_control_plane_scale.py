"""Tier-1 receipts for the incremental control plane (PR 9).

The steady-state contract: a cycle costs O(dirty work), not O(fleet).
These tests pin that down where a benchmark can't — by *counting* the
work units (persister reads, deploy-plan steps visited) at two fleet
sizes and asserting the counts track the dirty set, plus the
snapshot-API consistency guarantee under concurrent status ingest that
the lock-free HTTP path relies on.
"""

import random
import threading

from dcos_commons_tpu.agent.fake import FakeCluster
from dcos_commons_tpu.agent.inventory import AgentInfo, PortRange
from dcos_commons_tpu.http.queries import PlanQueries, PodQueries
from dcos_commons_tpu.plan import Status
from dcos_commons_tpu.plan.elements import DeploymentStep
from dcos_commons_tpu.scheduler import ServiceScheduler
from dcos_commons_tpu.specification import load_service_yaml_str
from dcos_commons_tpu.state import MemPersister
from dcos_commons_tpu.state.tasks import TaskState


class CountingPersister(MemPersister):
    """MemPersister that counts reads — the regression meter for
    ``fetch_statuses()``/``fetch_task_names()`` full-listing bugs: a
    warm scheduler cycle with a K-task dirty set must do O(K) reads,
    never an O(fleet) re-listing."""

    def __init__(self):
        super().__init__()
        self.reads = 0

    def get(self, path):
        self.reads += 1
        return super().get(path)

    def get_children(self, path):
        self.reads += 1
        return super().get_children(path)


def _yml(n):
    return f"""
name: bench
pods:
  web:
    count: {n}
    tasks:
      server:
        goal: RUNNING
        cmd: run
        cpus: 0.1
        memory: 32
plans:
  deploy:
    strategy: parallel
    phases:
      web-deploy:
        pod: web
        strategy: parallel
"""


def _deployed(n):
    """A fleet of ``n`` web pods deployed to COMPLETE over a counting
    persister, caches warm (one quiet cycle run after the ramp)."""
    agents = [AgentInfo(agent_id=f"a{i}", hostname=f"h{i}", cpus=64,
                        memory_mb=262144, disk_mb=1 << 20,
                        ports=(PortRange(1025, 32000),))
              for i in range(max(1, n // 10))]
    cluster = FakeCluster(agents)
    persister = CountingPersister()
    sched = ServiceScheduler(load_service_yaml_str(_yml(n), {}),
                             persister, cluster)
    sched.cycle_batch_size = 512
    for _ in range(10 * n + 100):
        sched.run_cycle()
        if sched.plan("deploy").status is Status.COMPLETE:
            break
    assert sched.plan("deploy").status is Status.COMPLETE
    sched.cycle_batch_size = type(sched).cycle_batch_size
    sched.run_cycle()  # warm every generation-keyed cache
    return sched, cluster, persister


def _crash(cluster, rng, k):
    live = cluster.live_tasks()
    victims = rng.sample(live, k)
    for t in victims:
        cluster.send_status(t.task_id, TaskState.FAILED, message="churn")
    return victims


class TestCycleCostScaling:
    def test_quiet_cycle_reads_are_constant(self):
        """No dirty work -> near-zero persister reads, independent of
        fleet size (the fetch_statuses full-listing regression guard)."""
        reads = {}
        for n in (100, 1000):
            sched, _, persister = _deployed(n)
            before = persister.reads
            sched.run_cycle()
            reads[n] = persister.reads - before
        # a quiet cycle may touch a handful of bookkeeping keys, but
        # never one per task
        assert reads[100] < 50, reads
        assert reads[1000] < 50, reads
        assert reads[1000] <= reads[100] + 10, reads

    def test_persister_reads_track_dirty_set_not_fleet(self):
        """Crashing K tasks costs O(K) reads at 100 and at 1000 tasks:
        the 10x fleet pays no more than a constant extra."""
        K = 5
        reads = {}
        for n in (100, 1000):
            sched, cluster, persister = _deployed(n)
            rng = random.Random(7)
            _crash(cluster, rng, K)
            before = persister.reads
            sched.run_cycle()   # ingest FAILED, recovery relaunches
            sched.run_cycle()   # ingest RUNNING from the relaunches
            reads[n] = persister.reads - before
        assert reads[100] < 80 * K, reads
        assert reads[1000] <= reads[100] + 40, reads

    def test_steps_visited_track_dirty_set_not_fleet(self, monkeypatch):
        """Status routing and candidate selection visit the dirty
        steps, not the whole 1000-step deploy plan."""
        K = 5
        visits = {}
        counted = {"n": 0}
        orig = DeploymentStep.update_status

        def counting(self, status):
            counted["n"] += 1
            return orig(self, status)

        monkeypatch.setattr(DeploymentStep, "update_status", counting)
        for n in (100, 1000):
            sched, cluster, persister = _deployed(n)
            rng = random.Random(7)
            _crash(cluster, rng, K)
            counted["n"] = 0
            sched.run_cycle()
            sched.run_cycle()
            visits[n] = counted["n"]
        # each crash surfaces a FAILED + a relaunch RUNNING status (plus
        # recovery-plan steps); none of it scales with the fleet
        assert visits[100] <= 12 * K, visits
        assert visits[1000] <= visits[100] + 10, visits


class TestSnapshotConsistency:
    def test_pod_snapshot_under_concurrent_ingest(self):
        """The HTTP pod surface stays well-formed and lock-free-fresh
        while statuses land concurrently, and converges to the state
        store once the storm stops."""
        sched, cluster, _ = _deployed(60)
        pods = PodQueries(sched)
        plans = PlanQueries(sched)
        stop = threading.Event()
        errors = []

        def storm():
            rng = random.Random(3)
            try:
                while not stop.is_set():
                    _crash(cluster, rng, 2)
                    sched.run_cycle()
            except Exception as e:  # surfaced below
                errors.append(e)

        th = threading.Thread(target=storm, daemon=True)
        th.start()
        valid_states = {s.value for s in TaskState} | {"NO_STATUS"}
        try:
            for _ in range(60):
                body = pods.status_all()
                for pod_body in body["pods"]:
                    assert pod_body["name"].startswith("web-")
                    for t in pod_body["tasks"]:
                        assert t["name"], t
                        assert t["status"] in valid_states, t
                one = pods.status("web-0")
                assert one["name"] == "web-0"
                _, plan_body = plans.get("deploy")
                assert plan_body["name"] == "deploy"
        finally:
            stop.set()
            th.join(timeout=30)
        assert not errors, errors
        sched.run_until_quiet()
        # converged: snapshot bodies now mirror the state store exactly
        body = pods.status("web-0")
        for t in body["tasks"]:
            st = sched.state.fetch_status(t["name"])
            assert t["status"] == (st.state.value if st else "NO_STATUS")
            rec = sched.state.fetch_task(t["name"])
            assert t["agentId"] == (rec.agent_id if rec else None)
