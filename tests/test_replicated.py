"""Replicated state backend tests.

Reference behaviors mirrored: ``curator/CuratorPersisterTest`` (atomic
setMany transactions), ``curator/CuratorLocker`` (only one scheduler
instance may act), and the HA property the reference gets from the ZK
ensemble: lose the scheduler host, a standby resumes from replica state.
"""

import threading
import time

import pytest

from dcos_commons_tpu.agent.fake import FakeCluster
from dcos_commons_tpu.plan import Status
from dcos_commons_tpu.scheduler import ServiceScheduler
from dcos_commons_tpu.specification import load_service_yaml_str
from dcos_commons_tpu.state import (LockError, NotFoundError, QuorumError,
                                    ReplicatedLock, ReplicatedPersister,
                                    StateReplicaServer, open_replicated)
from dcos_commons_tpu.testing.simulation import default_agents
from tests._crypto import requires_cryptography

# every replica hop rides the TLS transport, which needs the optional
# cryptography wheel — absent wheel is an environment gap, not a failure
pytestmark = requires_cryptography


@pytest.fixture()
def ensemble(tmp_path):
    servers = [StateReplicaServer(str(tmp_path / f"replica-{i}"), port=0)
               for i in range(3)]
    for s in servers:
        s.start()
    endpoints = [f"http://127.0.0.1:{s.port}" for s in servers]
    try:
        yield servers, endpoints, tmp_path
    finally:
        for s in servers:
            try:
                s.stop()
            except Exception:
                pass


class TestReplicatedPersister:
    """The Persister conformance surface (mirrors TestPersister in
    test_state.py) against a live 3-replica ensemble."""

    def test_get_set_children_delete(self, ensemble):
        _, endpoints, _ = ensemble
        p = ReplicatedPersister(endpoints)
        p.set("a/b", b"1")
        p.set("a/c", b"2")
        assert p.get("a/b") == b"1"
        assert p.get_children("a") == ["b", "c"]
        with pytest.raises(NotFoundError):
            p.get("missing")
        p.recursive_delete("a/b")
        assert p.get_children("a") == ["c"]
        with pytest.raises(NotFoundError):
            p.recursive_delete("a/b")

    def test_set_many_atomic_and_delete(self, ensemble):
        _, endpoints, _ = ensemble
        p = ReplicatedPersister(endpoints)
        p.set("keep", b"k")
        p.set_many({"x/1": b"a", "x/2": b"b", "keep": None})
        assert p.get("x/1") == b"a" and p.get("x/2") == b"b"
        assert p.get_or_none("keep") is None

    def test_state_survives_client_reopen(self, ensemble):
        _, endpoints, _ = ensemble
        p = ReplicatedPersister(endpoints)
        p.set("tasks/t0", b"payload")
        p2 = ReplicatedPersister(endpoints)
        assert p2.get("tasks/t0") == b"payload"

    def test_writes_survive_one_replica_down(self, ensemble):
        servers, endpoints, _ = ensemble
        p = ReplicatedPersister(endpoints)
        p.set("before", b"1")
        servers[0].stop()
        p.set("during", b"2")  # 2/3 still a majority
        p2 = ReplicatedPersister(endpoints)
        assert p2.get("before") == b"1" and p2.get("during") == b"2"

    def test_majority_loss_refuses_writes(self, ensemble):
        servers, endpoints, _ = ensemble
        p = ReplicatedPersister(endpoints, timeout_s=1.0)
        servers[0].stop()
        servers[1].stop()
        with pytest.raises(QuorumError):
            p.set("x", b"1")
        with pytest.raises(QuorumError):
            ReplicatedPersister(endpoints, timeout_s=1.0)

    def test_restarted_stale_replica_is_resynced(self, ensemble, tmp_path):
        servers, endpoints, _ = ensemble
        p = ReplicatedPersister(endpoints)
        p.set("a", b"1")
        servers[0].stop()
        p.set("b", b"2")  # replica 0 misses this write
        restarted = StateReplicaServer(str(tmp_path / "replica-0"), port=0)
        restarted.start()
        endpoints2 = [f"http://127.0.0.1:{restarted.port}"] + endpoints[1:]
        try:
            # next write 409s on the stale member and pushes a snapshot
            p2 = ReplicatedPersister(endpoints2)
            p2.set("c", b"3")
            solo = ReplicatedPersister(
                [endpoints2[0]])  # quorum of 1: reads replica 0 alone
            assert solo.get("b") == b"2" and solo.get("c") == b"3"
        finally:
            restarted.stop()


class TestReplicatedLock:
    def test_second_owner_blocked_until_release(self, ensemble):
        _, endpoints, _ = ensemble
        lock1 = ReplicatedLock(endpoints, "sched-a", ttl_s=5.0,
                               timeout_s=5.0)
        with pytest.raises(LockError):
            ReplicatedLock(endpoints, "sched-b", ttl_s=5.0, timeout_s=1.0,
                           poll_interval_s=0.2)
        lock1.release()
        lock2 = ReplicatedLock(endpoints, "sched-b", ttl_s=5.0,
                               timeout_s=5.0)
        lock2.release()

    def test_crashed_holder_expires(self, ensemble):
        _, endpoints, _ = ensemble
        # holder "crashes": never releases, never renews
        lock1 = ReplicatedLock(endpoints, "sched-a", ttl_s=0.8,
                               timeout_s=5.0)
        lock1._stop.set()  # kill the renewal thread (simulated crash)
        lock1._thread.join(timeout=5)
        t0 = time.monotonic()
        lock2 = ReplicatedLock(endpoints, "sched-b", ttl_s=5.0,
                               timeout_s=10.0, poll_interval_s=0.1)
        assert time.monotonic() - t0 >= 0.3  # waited out the TTL
        lock2.release()


class TestFencingAndPoisoning:
    def test_deposed_writer_cannot_commit_or_rollback(self, ensemble):
        """A revived ex-leader's writes are fenced by the successor's
        lease: they fail quorum, poison the old client, and never roll
        the ensemble back."""
        _, endpoints, _ = ensemble
        lock_a = ReplicatedLock(endpoints, "sched-a", ttl_s=0.6,
                                timeout_s=5.0)
        p_a = ReplicatedPersister(endpoints, owner="sched-a")
        p_a.set("committed/by-a", b"1")
        # A stalls: renewal stops, lease lapses
        lock_a._stop.set()
        lock_a._thread.join(timeout=5)
        lock_b = ReplicatedLock(endpoints, "sched-b", ttl_s=30.0,
                                timeout_s=10.0, poll_interval_s=0.1)
        p_b = ReplicatedPersister(endpoints, owner="sched-b")
        p_b.set("committed/by-b", b"2")
        # A wakes with a pending write: fenced everywhere, poisoned
        with pytest.raises(QuorumError, match="deposed|poisoned"):
            p_a.set("stale/rollback-attempt", b"X")
        with pytest.raises(QuorumError):  # stays poisoned
            p_a.set("another", b"Y")
        # B's committed writes survived; A's fenced write never landed
        p_check = ReplicatedPersister(endpoints, owner="sched-b")
        assert p_check.get("committed/by-b") == b"2"
        assert p_check.get_or_none("stale/rollback-attempt") is None
        lock_b.release()

    def test_rollback_blocked_even_after_all_leases_expire(self, ensemble):
        """The nastier variant: A is suspended past its TTL, successor B
        commits and then crashes, B's lease also expires — the resumed A
        still must not erase B's committed writes with its stale
        snapshot (log rewind requires holding a live lease)."""
        _, endpoints, _ = ensemble
        lock_a = ReplicatedLock(endpoints, "sched-a", ttl_s=0.5,
                                timeout_s=5.0)
        p_a = ReplicatedPersister(endpoints, owner="sched-a")
        p_a.set("base", b"0")
        lock_a._stop.set()  # A suspended
        lock_a._thread.join(timeout=5)
        lock_b = ReplicatedLock(endpoints, "sched-b", ttl_s=0.5,
                                timeout_s=10.0, poll_interval_s=0.1)
        p_b = ReplicatedPersister(endpoints, owner="sched-b")
        p_b.set("committed/by-b", b"2")
        lock_b._stop.set()  # B crashes; its lease expires too
        lock_b._thread.join(timeout=5)
        time.sleep(0.7)
        # A resumes with a pending write at a stale index: all replicas
        # 409, no lease fences, but the rewind-resync is rejected
        with pytest.raises(QuorumError):
            p_a.set("stale/write", b"X")
        p_check = ReplicatedPersister(endpoints)
        assert p_check.get("committed/by-b") == b"2"
        assert p_check.get_or_none("stale/write") is None

    def test_conflicting_write_at_head_not_phantom_acked(self, ensemble):
        """Two lock-less writers at the same index must not both believe
        they committed: the replica compares the entry digest and rejects
        the divergent one instead of phantom-acking a 'duplicate'."""
        _, endpoints, _ = ensemble
        p1 = ReplicatedPersister(endpoints)
        p2 = ReplicatedPersister(endpoints)  # same next_index as p1
        p1.set("winner", b"1")
        with pytest.raises(QuorumError):
            p2.set("loser", b"2")  # same index, different payload
        p_check = ReplicatedPersister(endpoints)
        assert p_check.get("winner") == b"1"
        assert p_check.get_or_none("loser") is None

    def test_failed_quorum_poisons_client(self, ensemble):
        servers, endpoints, _ = ensemble
        p = ReplicatedPersister(endpoints, timeout_s=1.0)
        servers[0].stop()
        servers[1].stop()
        with pytest.raises(QuorumError):
            p.set("x", b"1")
        # every subsequent op refuses: the mirror may be ahead
        with pytest.raises(QuorumError):
            p.set("y", b"2")
        with pytest.raises(QuorumError):
            p.get("x")

    def test_lease_survives_replica_restart(self, ensemble, tmp_path):
        servers, endpoints, _ = ensemble
        lock_a = ReplicatedLock(endpoints, "sched-a", ttl_s=30.0,
                                timeout_s=5.0)
        # roll-restart two replicas while A is healthy
        restarted = []
        for i in (0, 1):
            servers[i].stop()
            r = StateReplicaServer(str(tmp_path / f"replica-{i}"), port=0)
            r.start()
            restarted.append(r)
        endpoints2 = [f"http://127.0.0.1:{r.port}" for r in restarted] \
            + endpoints[2:]
        try:
            with pytest.raises(LockError):  # lease survived the restarts
                ReplicatedLock(endpoints2, "sched-b", ttl_s=5.0,
                               timeout_s=1.0, poll_interval_s=0.2)
        finally:
            for r in restarted:
                r.stop()
            lock_a.release()

    def test_holder_steps_down_after_losing_majority(self, ensemble):
        servers, endpoints, _ = ensemble
        lost = threading.Event()
        lock = ReplicatedLock(endpoints, "sched-a", ttl_s=0.6,
                              timeout_s=5.0, request_timeout_s=0.5,
                              on_lost=lost.set)
        for s in servers:
            s.stop()
        assert lost.wait(timeout=10), "on_lost never fired"


class TestEnsembleSecret:
    def test_secret_required_when_configured(self, tmp_path):
        server = StateReplicaServer(str(tmp_path / "r0"), port=0,
                                    secret="hunter2")
        server.start()
        endpoints = [f"http://127.0.0.1:{server.port}"]
        try:
            with pytest.raises(QuorumError):
                ReplicatedPersister(endpoints, timeout_s=1.0)  # no secret
            p = ReplicatedPersister(endpoints, secret="hunter2")
            p.set("a", b"1")
            assert p.get("a") == b"1"
            with pytest.raises(LockError):
                ReplicatedLock(endpoints, "x", timeout_s=0.5,
                               poll_interval_s=0.2)  # no secret
            lock = ReplicatedLock(endpoints, "x", timeout_s=5.0,
                                  secret="hunter2")
            lock.release()
        finally:
            server.stop()


YML = """
name: hasvc
pods:
  hello:
    count: 2
    tasks:
      server: {goal: RUNNING, cmd: run, cpus: 0.5, memory: 128}
"""


class TestSchedulerFailover:
    """The VERDICT's done-criterion: kill the primary scheduler (and one
    replica), a standby acquires the lease, resumes from replica state,
    and reconciles without relaunching anything."""

    def test_standby_resumes_from_replica_state(self, ensemble):
        servers, endpoints, _ = ensemble
        agents = default_agents(3)

        # primary scheduler deploys to COMPLETE
        persister_a, lock_a = open_replicated(endpoints, "sched-a",
                                              ttl_s=0.8)
        cluster = FakeCluster(agents)
        sched_a = ServiceScheduler(load_service_yaml_str(YML), persister_a,
                                   cluster)
        for _ in range(30):
            sched_a.run_cycle()
            if sched_a.plan("deploy").status is Status.COMPLETE:
                break
        assert sched_a.plan("deploy").status is Status.COMPLETE
        tasks_before = {t.task_name: t.task_id
                        for t in sched_a.state.fetch_tasks()}
        assert len(tasks_before) == 2

        # primary host dies: scheduler gone (lease not released), and one
        # replica lost with it
        lock_a._stop.set()
        lock_a._thread.join(timeout=5)
        servers[0].stop()

        # standby comes up against the surviving majority
        persister_b, lock_b = open_replicated(endpoints, "sched-b",
                                              ttl_s=5.0, timeout_s=15.0)
        try:
            sched_b = ServiceScheduler(load_service_yaml_str(YML),
                                       persister_b, cluster)
            # state carried over: same tasks, deploy plan rebuilt COMPLETE
            tasks_after = {t.task_name: t.task_id
                           for t in sched_b.state.fetch_tasks()}
            assert tasks_after == tasks_before
            sched_b.reconcile()
            for _ in range(10):
                sched_b.run_cycle()
            assert sched_b.plan("deploy").status is Status.COMPLETE
            assert {t.task_name: t.task_id
                    for t in sched_b.state.fetch_tasks()} == tasks_before
            # and the standby can keep writing (config updates etc.)
            sched_b.state.store_property("owner", b"sched-b")
        finally:
            lock_b.release()
