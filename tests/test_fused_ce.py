"""Parity tests: fused linear-cross-entropy vs the reference loss path.

The fused op (ops/losses.py) must be a drop-in for
``softmax_cross_entropy(qmm(x, lm_head), labels, ...)`` — same value,
same gradients — while never materializing the full [B, S, V] fp32
logits tensor (the jaxpr test checks that claim structurally, so it
holds on CPU exactly as it does on TPU).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tests._jax_cpu  # noqa: F401

from jax.sharding import NamedSharding, PartitionSpec as P

from dcos_commons_tpu.models import llama, train
from dcos_commons_tpu.ops import losses
from dcos_commons_tpu.ops.quant import dequantize, quantize
from dcos_commons_tpu.parallel.mesh import MeshSpec

B, S, D, V = 2, 16, 32, 97


def _data(key=0, s=S, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(key), 4)
    x = jax.random.normal(ks[0], (B, s, D), dtype)
    w = (jax.random.normal(ks[1], (D, V), jnp.float32) * D ** -0.5
         ).astype(dtype)
    labels = jax.random.randint(ks[2], (B, s), 0, V)
    mask = (jax.random.uniform(ks[3], (B, s)) > 0.3).astype(jnp.float32)
    return x, w, labels, mask


def _ref(x, w, labels, mask=None, z_loss=0.0):
    logits = (x @ w).astype(jnp.float32)
    return losses.softmax_cross_entropy(logits, labels, mask=mask,
                                        z_loss=z_loss)


# ------------------------------------------------------------- value parity

@pytest.mark.parametrize("mask_on,z_loss,block", [
    (False, 0.0, 4),
    (True, 1e-4, 4),
    (True, 0.0, 16),     # block == S
    (False, 1e-4, 5),    # S % block != 0 (odd tail, masked padding)
])
def test_value_and_accuracy_parity(mask_on, z_loss, block):
    x, w, labels, mask = _data()
    m = mask if mask_on else None
    loss_ref, acc_ref = _ref(x, w, labels, mask=m, z_loss=z_loss)
    loss_f, acc_f = losses.fused_linear_cross_entropy(
        x, w, labels, mask=m, z_loss=z_loss, block_size=block)
    np.testing.assert_allclose(float(loss_f), float(loss_ref), atol=1e-4)
    np.testing.assert_allclose(float(acc_f), float(acc_ref), atol=1e-6)


# -------------------------------------------------------------- grad parity

@pytest.mark.parametrize("mask_on,z_loss,block", [
    (False, 0.0, 4),
    (True, 1e-4, 4),
    (False, 1e-4, 5),    # odd S % block
])
def test_grad_parity(mask_on, z_loss, block):
    x, w, labels, mask = _data(key=1)
    m = mask if mask_on else None

    def ref_loss(x, w):
        return _ref(x, w, labels, mask=m, z_loss=z_loss)[0]

    def fused_loss(x, w):
        return losses.fused_linear_cross_entropy(
            x, w, labels, mask=m, z_loss=z_loss, block_size=block)[0]

    gx_r, gw_r = jax.grad(ref_loss, argnums=(0, 1))(x, w)
    gx_f, gw_f = jax.grad(fused_loss, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_r),
                               atol=1e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_r),
                               atol=1e-3, rtol=1e-3)


def test_quantized_head_value_and_dx_parity():
    """int8 QTensor lm_head: fused matches reference through qmm, and dx
    flows without a dequantized [D, V] copy."""
    x, _, labels, _ = _data(key=2)
    w = quantize(jax.random.normal(jax.random.key(9), (D, V)) * D ** -0.5)
    loss_ref, acc_ref = losses.softmax_cross_entropy(
        (x @ dequantize(w, jnp.float32)).astype(jnp.float32), labels)
    loss_f, acc_f = losses.fused_linear_cross_entropy(
        x, w, labels, block_size=4)
    np.testing.assert_allclose(float(loss_f), float(loss_ref), atol=1e-4)
    np.testing.assert_allclose(float(acc_f), float(acc_ref), atol=1e-6)

    def ref_loss(x):
        return losses.softmax_cross_entropy(
            (x @ dequantize(w, jnp.float32)).astype(jnp.float32), labels)[0]

    def fused_loss(x):
        return losses.fused_linear_cross_entropy(
            x, w, labels, block_size=4)[0]

    gx_r = jax.grad(ref_loss)(x)
    gx_f = jax.grad(fused_loss)(x)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_r),
                               atol=1e-3, rtol=1e-3)


def test_compute_accuracy_false_skips_argmax():
    x, w, labels, _ = _data(key=3)
    loss_ref, _ = _ref(x, w, labels)
    loss, acc = losses.fused_linear_cross_entropy(
        x, w, labels, block_size=4, compute_accuracy=False)
    assert acc is None
    np.testing.assert_allclose(float(loss), float(loss_ref), atol=1e-4)
    # the reference flag behaves identically
    loss2, acc2 = losses.softmax_cross_entropy(
        (x @ w).astype(jnp.float32), labels, compute_accuracy=False)
    assert acc2 is None
    np.testing.assert_allclose(float(loss2), float(loss_ref), atol=1e-6)


# ------------------------------------------------- tp-sharded lm_head mesh

def test_tp_sharded_lm_head_parity():
    """Fused loss under GSPMD with the lm_head sharded over tp: same
    value/grads as the single-device run — the blockwise logsumexp must
    partition over the vocab axis like the unfused loss did."""
    vs = 96  # divisible by tp=4 (the sharded-axis requirement)
    ks = jax.random.split(jax.random.key(4), 4)
    x = jax.random.normal(ks[0], (B, S, D))
    w = jax.random.normal(ks[1], (D, vs)) * D ** -0.5
    labels = jax.random.randint(ks[2], (B, S), 0, vs)
    mask = (jax.random.uniform(ks[3], (B, S)) > 0.3).astype(jnp.float32)
    loss_ref, acc_ref = _ref(x, w, labels, mask=mask, z_loss=1e-4)
    mesh = MeshSpec(dp=2, tp=4).build()
    xs = jax.device_put(x, NamedSharding(mesh, P("dp", None, None)))
    ws = jax.device_put(w, NamedSharding(mesh, P(None, "tp")))
    ls = jax.device_put(labels, NamedSharding(mesh, P("dp", None)))
    ms = jax.device_put(mask, NamedSharding(mesh, P("dp", None)))

    @jax.jit
    def fused(x, w, labels, mask):
        loss, acc = losses.fused_linear_cross_entropy(
            x, w, labels, mask=mask, z_loss=1e-4, block_size=4)
        return loss, acc

    loss_f, acc_f = fused(xs, ws, ls, ms)
    np.testing.assert_allclose(float(loss_f), float(loss_ref), atol=1e-4)
    np.testing.assert_allclose(float(acc_f), float(acc_ref), atol=1e-6)

    gx_r, gw_r = jax.grad(
        lambda x, w: _ref(x, w, labels, mask=mask, z_loss=1e-4)[0],
        argnums=(0, 1))(x, w)
    gx_f, gw_f = jax.jit(jax.grad(
        lambda x, w: losses.fused_linear_cross_entropy(
            x, w, ls, mask=ms, z_loss=1e-4, block_size=4)[0],
        argnums=(0, 1)))(xs, ws)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_r),
                               atol=1e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_r),
                               atol=1e-3, rtol=1e-3)


# ------------------------------------------------------- llama loss routing

def _tiny_pair(**kw):
    cfg = llama.LlamaConfig.tiny(n_layers=2, fused_ce=True,
                                 fused_ce_block=8, **kw)
    return cfg, dataclasses.replace(cfg, fused_ce=False)


def test_llama_loss_fn_fused_matches_unfused():
    cfg, cfg_ref = _tiny_pair()
    params = llama.init_params(cfg, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 33), 0,
                              cfg.vocab_size)  # odd S-1 % block
    loss_f, acc_f = llama.loss_fn(cfg, params, toks)
    loss_r, acc_r = llama.loss_fn(cfg_ref, params, toks)
    np.testing.assert_allclose(float(loss_f), float(loss_r), atol=1e-3)
    np.testing.assert_allclose(float(acc_f), float(acc_r), atol=1e-6)


@pytest.mark.skipif(not hasattr(jax, "shard_map"),
                    reason="jax.shard_map unavailable (MoE layer needs it)")
def test_llama_moe_loss_fused_matches_unfused():
    from dcos_commons_tpu.parallel.moe import MoEConfig
    cfg, cfg_ref = _tiny_pair(attn_impl="dense")
    mesh = MeshSpec(dp=4, ep=2).build()
    moe_cfg = MoEConfig(num_experts=2)
    params = llama.init_moe_params(cfg, 2, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 17), 0,
                              cfg.vocab_size)
    loss_f, _ = llama.loss_fn_moe(cfg, params, toks, mesh, moe_cfg)
    loss_r, _ = llama.loss_fn_moe(cfg_ref, params, toks, mesh, moe_cfg)
    np.testing.assert_allclose(float(loss_f), float(loss_r), atol=1e-3)


# ---------------------------------------- no [B, S, V] fp32 in the jaxpr
# The walker and the budget rule live in dcos_commons_tpu.analysis now
# (the J1 CI gate); this test pins the fused-CE guarantee through the
# same code path the lint gate runs.

def test_fused_train_step_never_materializes_full_logits():
    from dcos_commons_tpu.analysis import rule_j1_oversized_fp32, walk_avals
    # vocab is scaled up so the full-logits tensor (1 MiB) is 2x the
    # lm_head grad and 4x the fp32 attention scores — a budget just under
    # it can only be tripped by the materialization itself
    cfg = llama.LlamaConfig.tiny(n_layers=2, vocab_size=2048,
                                 fused_ce=True, fused_ce_block=8)
    params = llama.init_params(cfg, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 65), 0,
                              cfg.vocab_size)
    full = (2, 64, cfg.vocab_size)  # [B, S-1, V]
    budget = 2 * 64 * cfg.vocab_size * 4 - 1

    def grads(p, t):
        return jax.value_and_grad(
            lambda p_: llama.loss_fn(cfg, p_, t)[0])(p)

    jaxpr = jax.make_jaxpr(grads)(params, toks)
    hits = [a for a in walk_avals(jaxpr.jaxpr)
            if getattr(a, "shape", None) == full
            and getattr(a, "dtype", None) == jnp.float32]
    assert not hits, f"full fp32 logits materialized: {hits}"
    assert not rule_j1_oversized_fp32(jaxpr, budget, "fused")

    # sanity: the UNFUSED step does contain it (walker + rule both see it)
    cfg_ref = dataclasses.replace(cfg, fused_ce=False)

    def grads_ref(p, t):
        return jax.value_and_grad(
            lambda p_: llama.loss_fn(cfg_ref, p_, t)[0])(p)

    jaxpr_ref = jax.make_jaxpr(grads_ref)(params, toks)
    hits_ref = [a for a in walk_avals(jaxpr_ref.jaxpr)
                if getattr(a, "shape", None) == full
                and getattr(a, "dtype", None) == jnp.float32]
    assert hits_ref, "reference path should materialize full logits"
    j1 = rule_j1_oversized_fp32(jaxpr_ref, budget, "unfused")
    assert j1 and all(f.code == "J1" for f in j1)


# -------------------------------------------------- grad-accum microbatching

def test_grad_accum_matches_single_pass():
    cfg = llama.LlamaConfig.tiny(n_layers=2, fused_ce=True,
                                 fused_ce_block=8)
    params = llama.init_params(cfg, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (4, 17), 0,
                              cfg.vocab_size)
    opt = train.make_optimizer(lr=1e-3, warmup=1, decay_steps=100)
    s1 = train.make_train_step(lambda p, b: llama.loss_fn(cfg, p, b), opt)
    s4 = train.make_train_step(lambda p, b: llama.loss_fn(cfg, p, b), opt,
                               grad_accum=4)
    pa = jax.tree.map(jnp.copy, params)
    pb = jax.tree.map(jnp.copy, params)
    p1, _, out1 = s1(pa, opt.init(pa), toks)
    p4, _, out4 = s4(pb, opt.init(pb), toks)
    np.testing.assert_allclose(float(out1["loss"]), float(out4["loss"]),
                               atol=1e-3)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p4)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32), atol=1e-2)


def test_grad_accum_rejects_indivisible_batch():
    cfg = llama.LlamaConfig.tiny(n_layers=2)
    params = llama.init_params(cfg, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (4, 17), 0,
                              cfg.vocab_size)
    opt = train.make_optimizer(lr=1e-3, warmup=1, decay_steps=100)
    s3 = train.make_train_step(lambda p, b: llama.loss_fn(cfg, p, b), opt,
                               grad_accum=3)
    with pytest.raises(ValueError, match="not divisible"):
        s3(params, opt.init(params), toks)


def test_make_train_step_validates_grad_accum():
    opt = train.make_optimizer()
    with pytest.raises(ValueError):
        train.make_train_step(lambda p, b: (0.0, 0.0), opt, grad_accum=0)
    with pytest.raises(NotImplementedError):
        train.make_train_step(lambda p, b: (0.0, 0.0), opt,
                              has_aux_state=True, grad_accum=2)


# ------------------------------------------------------- spec knob plumbing

def test_scenario_renders_loss_head_knobs():
    """The longctx spec routes FUSED_CE / GRAD_ACCUM env knobs into the
    worker cmd, parseable the way the scheduler parses spec booleans."""
    from dcos_commons_tpu.specification import yaml_bool
    from frameworks.jax import scenarios

    spec = scenarios.load_scenario(
        "longctx", env=scenarios.scenario_env({"GRAD_ACCUM": "4"}))
    pod = next(p for p in spec.pods if p.type == "worker")
    cmd = next(t for t in pod.tasks if t.name == "train").cmd
    assert "--fused-ce true" in cmd
    assert "--grad-accum 4" in cmd
    assert yaml_bool("true") and not yaml_bool("false")
