"""Metrics registry tests (reference metrics/Metrics.java + PlanReporter +
testing/sdk_metrics.py assertions)."""

import socket

from dcos_commons_tpu.agent import AgentInfo, FakeCluster, PortRange
from dcos_commons_tpu.metrics import MetricsRegistry, PlanReporter
from dcos_commons_tpu.scheduler import ServiceScheduler
from dcos_commons_tpu.specification import load_service_yaml_str
from dcos_commons_tpu.state import MemPersister

YML = """
name: metricsvc
pods:
  hello:
    count: 2
    tasks:
      server: {goal: RUNNING, cmd: ./run, cpus: 0.1, memory: 64}
"""


def make_scheduler(metrics):
    agents = [AgentInfo(agent_id="a0", hostname="h0", cpus=4, memory_mb=8192,
                        disk_mb=10000, ports=(PortRange(10000, 10100),))]
    spec = load_service_yaml_str(YML)
    return ServiceScheduler(spec, MemPersister(), FakeCluster(agents),
                            metrics=metrics)


class TestRegistry:
    def test_scheduler_counters(self):
        m = MetricsRegistry()
        sched = make_scheduler(m)
        sched.run_until_quiet()
        data = m.to_dict()
        assert data["counters"]["scheduler.cycles"] >= 1
        assert data["counters"]["operations.launch"] == 2
        assert data["counters"]["task_status.task_running"] >= 2

    def test_plan_gauges(self):
        m = MetricsRegistry()
        sched = make_scheduler(m)
        PlanReporter(m, sched)
        sched.run_until_quiet()
        assert m.to_dict()["gauges"]["plan_status.deploy"] == 0  # COMPLETE

    def test_prometheus_exposition(self):
        m = MetricsRegistry()
        sched = make_scheduler(m)
        PlanReporter(m, sched)
        sched.run_until_quiet()
        text = m.to_prometheus()
        assert "# TYPE operations_launch counter" in text
        assert "plan_status_deploy 0" in text

    def test_timer(self):
        m = MetricsRegistry()
        with m.time("work"):
            pass
        stats = m.to_dict()["timers"]["work"]
        assert stats["count"] == 1
        assert stats["max_s"] >= 0

    def test_statsd_push(self):
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5)
        port = recv.getsockname()[1]
        m = MetricsRegistry()
        m.configure_statsd("127.0.0.1", port)
        m.counter("ops.launch", 3)
        datagram = recv.recv(1024).decode()
        assert datagram == "tpu_sdk.ops.launch:3|c"
        recv.close()


def test_agents_registered_gauge():
    from dcos_commons_tpu.agent import FakeCluster
    from dcos_commons_tpu.metrics import MetricsRegistry
    from dcos_commons_tpu.scheduler import ServiceScheduler
    from dcos_commons_tpu.specification import load_service_yaml_str
    from dcos_commons_tpu.state import MemPersister
    from dcos_commons_tpu.testing.simulation import default_agents
    metrics = MetricsRegistry()
    cluster = FakeCluster(default_agents(3))
    ServiceScheduler(load_service_yaml_str("""
name: m
pods:
  p:
    count: 1
    tasks:
      t: {goal: RUNNING, cmd: run, cpus: 0.1, memory: 32}
"""), MemPersister(), cluster, metrics=metrics)
    assert metrics.to_dict()["gauges"]["agents.registered"] == 3.0
    cluster.remove_agent("agent-2")
    assert metrics.to_dict()["gauges"]["agents.registered"] == 2.0
