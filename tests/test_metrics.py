"""Metrics registry tests (reference metrics/Metrics.java + PlanReporter +
testing/sdk_metrics.py assertions)."""

import random
import socket
import threading

from dcos_commons_tpu.agent import AgentInfo, FakeCluster, PortRange
from dcos_commons_tpu.metrics import MetricsRegistry, PlanReporter
from dcos_commons_tpu.scheduler import ServiceScheduler
from dcos_commons_tpu.specification import load_service_yaml_str
from dcos_commons_tpu.state import MemPersister

YML = """
name: metricsvc
pods:
  hello:
    count: 2
    tasks:
      server: {goal: RUNNING, cmd: ./run, cpus: 0.1, memory: 64}
"""


def make_scheduler(metrics):
    agents = [AgentInfo(agent_id="a0", hostname="h0", cpus=4, memory_mb=8192,
                        disk_mb=10000, ports=(PortRange(10000, 10100),))]
    spec = load_service_yaml_str(YML)
    return ServiceScheduler(spec, MemPersister(), FakeCluster(agents),
                            metrics=metrics)


class TestRegistry:
    def test_scheduler_counters(self):
        m = MetricsRegistry()
        sched = make_scheduler(m)
        sched.run_until_quiet()
        data = m.to_dict()
        assert data["counters"]["scheduler.cycles"] >= 1
        assert data["counters"]["operations.launch"] == 2
        assert data["counters"]["task_status.task_running"] >= 2

    def test_plan_gauges(self):
        m = MetricsRegistry()
        sched = make_scheduler(m)
        PlanReporter(m, sched)
        sched.run_until_quiet()
        assert m.to_dict()["gauges"]["plan_status.deploy"] == 0  # COMPLETE

    def test_prometheus_exposition(self):
        m = MetricsRegistry()
        sched = make_scheduler(m)
        PlanReporter(m, sched)
        sched.run_until_quiet()
        text = m.to_prometheus()
        assert "# TYPE operations_launch counter" in text
        assert "plan_status_deploy 0" in text

    def test_timer(self):
        m = MetricsRegistry()
        with m.time("work"):
            pass
        stats = m.to_dict()["timers"]["work"]
        assert stats["count"] == 1
        assert stats["max_s"] >= 0

    def test_statsd_push(self):
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5)
        port = recv.getsockname()[1]
        m = MetricsRegistry()
        m.configure_statsd("127.0.0.1", port)
        m.counter("ops.launch", 3)
        datagram = recv.recv(1024).decode()
        assert datagram == "tpu_sdk.ops.launch:3|c"
        recv.close()


class TestHistogramPercentiles:
    """The bucketed Timer percentiles must track an exact computation
    (utils.stats.percentiles) within the documented bucket resolution."""

    def test_lognormal_within_10pct(self):
        from dcos_commons_tpu.utils.stats import percentiles
        rng = random.Random(13)
        samples = [rng.lognormvariate(-3.0, 0.8) for _ in range(5000)]
        m = MetricsRegistry()
        for s in samples:
            m.observe("ttft_seconds", s)
        snap = m.to_dict()["timers"]["ttft_seconds"]
        exact = percentiles(samples, ndigits=9)
        for q in ("p50", "p95", "p99"):
            est, ref = snap[f"{q}_s"], exact[q]
            assert abs(est - ref) / ref < 0.10, \
                f"{q}: histogram {est} vs exact {ref}"

    def test_envelope_clamp(self):
        # a single sample: every percentile is that sample, not a bucket
        # midpoint outside the observed [min, max] envelope
        m = MetricsRegistry()
        m.observe("one", 0.2)
        snap = m.to_dict()["timers"]["one"]
        assert snap["p50_s"] == snap["p99_s"] == 0.2

    def test_out_of_range_samples(self):
        from dcos_commons_tpu.metrics import Timer
        t = Timer()
        t.record(1e-7)    # below the smallest bound
        t.record(5e3)     # beyond the largest bound
        t.record(-1.0)    # clamped to zero
        assert t.count == 3
        assert t.percentile(0.99) <= t.max_s
        assert t.percentile(0.01) >= t.min_s == 0.0


class TestPrometheusConformance:
    """Exposition discipline, validated with the same parser the CI smoke
    uses against live endpoints (tools/metrics_smoke.py)."""

    def _families(self, m):
        from tools.metrics_smoke import check_histograms, parse_exposition
        families = parse_exposition(m.to_prometheus())
        check_histograms(families)
        return families

    def test_timer_exports_histogram_and_gauges(self):
        m = MetricsRegistry()
        for v in (0.001, 0.01, 0.1):
            m.observe("router.ttft_seconds", v)
        text = m.to_prometheus()
        # the *_seconds timer name must not double the unit suffix
        assert "router_ttft_seconds_seconds" not in text
        assert "# TYPE router_ttft_seconds histogram" in text
        assert "# TYPE router_ttft_count counter" in text
        assert "# TYPE router_ttft_mean_seconds gauge" in text
        assert "# TYPE router_ttft_max_seconds gauge" in text
        fam = self._families(m)["router_ttft_seconds"]
        count = [v for n, _, v in fam["samples"]
                 if n == "router_ttft_seconds_count"]
        assert count == [3.0]

    def test_cumulative_buckets_nondecreasing(self):
        rng = random.Random(7)
        m = MetricsRegistry()
        for _ in range(500):
            m.observe("lat", rng.expovariate(20.0))
        fam = self._families(m)["lat_seconds"]
        buckets = [v for n, lbl, v in fam["samples"]
                   if n == "lat_seconds_bucket"]
        assert buckets == sorted(buckets)
        assert buckets[-1] == 500.0

    def test_name_collision_dedup(self):
        # "a.b" and "a/b" both sanitize to a_b; exposition must not emit
        # duplicate series — the later name gets a hash suffix
        m = MetricsRegistry()
        m.counter("a.b", 1)
        m.counter("a/b", 2)
        families = self._families(m)
        names = [n for fam in families.values()
                 for n, _, _ in fam["samples"]]
        assert len(names) == len(set(names)) == 2
        assert "a_b" in names
        suffixed = [n for n in names if n != "a_b"]
        assert suffixed and suffixed[0].startswith("a_b_")


class TestStatsdLifecycle:
    def _recv_socket(self):
        recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        recv.bind(("127.0.0.1", 0))
        recv.settimeout(5)
        return recv, recv.getsockname()[1]

    def test_push_gauges(self):
        recv, port = self._recv_socket()
        try:
            m = MetricsRegistry()
            m.configure_statsd("127.0.0.1", port)
            m.gauge("queue.depth", lambda: 7)
            m.gauge("broken", lambda: 1 / 0)    # skipped, not fatal
            m.gauge("not_numeric", lambda: "x")
            assert m.push_gauges() == 1
            assert recv.recv(1024).decode() == "tpu_sdk.queue.depth:7.0|g"
        finally:
            recv.close()

    def test_close_releases_socket(self):
        recv, port = self._recv_socket()
        try:
            m = MetricsRegistry()
            m.configure_statsd("127.0.0.1", port)
            pusher_sock = m._statsd._sock
            m.close()
            assert pusher_sock.fileno() == -1    # closed, fd released
            assert m.push_gauges() == 0          # statsd detached
            m.counter("after.close")             # no crash post-close
            m.close()                            # idempotent
        finally:
            recv.close()


class TestConcurrency:
    def test_parallel_counters_exact(self):
        m = MetricsRegistry()
        n_threads, n_incr = 8, 2000

        def work():
            for _ in range(n_incr):
                m.counter("hits")
                m.observe("lat_seconds", 0.001)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        data = m.to_dict()
        assert data["counters"]["hits"] == n_threads * n_incr
        assert data["timers"]["lat_seconds"]["count"] == n_threads * n_incr

    def test_gauge_supplier_may_reenter_registry(self):
        # suppliers run outside the registry lock, so a gauge that reads
        # the registry (a load gauge derived from counters, the ingress
        # pattern) must not deadlock to_dict()/to_prometheus()
        m = MetricsRegistry()
        m.counter("served", 5)
        m.gauge("served.copy",
                lambda: m.to_dict()["counters"]["served"])
        done = []

        def snap():
            done.append(m.to_dict()["gauges"]["served.copy"])

        t = threading.Thread(target=snap)
        t.start()
        t.join(timeout=10)
        assert not t.is_alive(), "to_dict() deadlocked on a reentrant gauge"
        assert done == [5.0]
        assert "served_copy 5.0" in m.to_prometheus()


def test_agents_registered_gauge():
    from dcos_commons_tpu.agent import FakeCluster
    from dcos_commons_tpu.metrics import MetricsRegistry
    from dcos_commons_tpu.scheduler import ServiceScheduler
    from dcos_commons_tpu.specification import load_service_yaml_str
    from dcos_commons_tpu.state import MemPersister
    from dcos_commons_tpu.testing.simulation import default_agents
    metrics = MetricsRegistry()
    cluster = FakeCluster(default_agents(3))
    ServiceScheduler(load_service_yaml_str("""
name: m
pods:
  p:
    count: 1
    tasks:
      t: {goal: RUNNING, cmd: run, cpus: 0.1, memory: 32}
"""), MemPersister(), cluster, metrics=metrics)
    assert metrics.to_dict()["gauges"]["agents.registered"] == 3.0
    cluster.remove_agent("agent-2")
    assert metrics.to_dict()["gauges"]["agents.registered"] == 2.0
