"""Elastic control plane tests (dcos_commons_tpu/scheduler/elastic.py).

Covers the three controllers (autoscaler, preemptor, backfill gate) plus
the back-pressure combinator and the rolling-window load gauges they
consume. The scale/preemption integration tests run the same two-service
fleet as the elastic chaos soak (chaos/elastic_soak.py) with the weather
turned off, so every protocol step is deterministic and inspectable.
"""

import pytest

from dcos_commons_tpu.chaos.elastic_soak import (AUTOSCALE, ElasticSoak,
                                                 SERVE_YML, TRAIN_YML)
from dcos_commons_tpu.chaos.engine import FaultConfig
from dcos_commons_tpu.metrics import MetricsRegistry
from dcos_commons_tpu.models.ingress import ServingFrontend
from dcos_commons_tpu.plan import Status
from dcos_commons_tpu.scheduler.elastic import (AutoscalerConfig,
                                                BackfillGate,
                                                HysteresisController,
                                                backpressure,
                                                pending_expansion_chips)
from dcos_commons_tpu.specification import load_service_yaml_str


# ------------------------------------------------------- back-pressure

class TestBackpressure:
    def test_empty_gauges_zero(self):
        assert backpressure({}) == 0.0

    def test_queue_fill_fraction(self):
        assert backpressure({"queue_depth": 4, "queue_capacity": 16}) \
            == pytest.approx(0.25)

    def test_shedding_pins_to_one(self):
        g = {"queue_depth": 1, "queue_capacity": 16, "shed": 3}
        assert backpressure(g) == 1.0

    def test_page_occupancy(self):
        g = {"pages_total": 100, "pages_free": 10}
        assert backpressure(g) == pytest.approx(0.9)

    def test_ttft_against_slo(self):
        g = {"ttft_p95_ms": 200.0}
        assert backpressure(g) == 0.0          # no SLO configured: ignored
        assert backpressure(g, ttft_slo_ms=200.0) == pytest.approx(0.8)
        assert backpressure(g, ttft_slo_ms=100.0) == 1.0  # clamped

    def test_max_over_signals(self):
        g = {"queue_depth": 2, "queue_capacity": 16,
             "pages_total": 10, "pages_free": 3}
        assert backpressure(g) == pytest.approx(0.7)


class TestAutoscalerConfig:
    def test_from_env_contract(self):
        env = {"AUTOSCALE_MIN": "2", "AUTOSCALE_MAX": "8",
               "AUTOSCALE_HIGH": "0.9", "AUTOSCALE_LOW": "0.1",
               "AUTOSCALE_DEBOUNCE": "4", "AUTOSCALE_COOLDOWN": "6",
               "AUTOSCALE_STEP_UP": "2", "AUTOSCALE_TTFT_SLO_MS": "250"}
        cfg = AutoscalerConfig.from_env("decode", env)
        assert cfg.pod_type == "decode"
        assert (cfg.min_count, cfg.max_count) == (2, 8)
        assert (cfg.high_pressure, cfg.low_pressure) == (0.9, 0.1)
        assert (cfg.debounce_ticks, cfg.cooldown_ticks) == (4, 6)
        assert cfg.step_up == 2 and cfg.step_down == 1
        assert cfg.ttft_slo_ms == 250.0

    def test_from_env_defaults(self):
        cfg = AutoscalerConfig.from_env("decode", {})
        assert (cfg.min_count, cfg.max_count) == (1, 4)
        assert cfg.ttft_slo_ms is None

    def test_validation(self):
        with pytest.raises(ValueError):
            AutoscalerConfig(pod_type="p", min_count=5, max_count=2)
        with pytest.raises(ValueError):
            AutoscalerConfig(pod_type="p", low_pressure=0.8,
                             high_pressure=0.4)
        with pytest.raises(ValueError):
            AutoscalerConfig(pod_type="p", debounce_ticks=0)


class TestReshardConfig:
    def test_from_env_contract(self):
        from dcos_commons_tpu.scheduler.elastic import ReshardConfig
        env = {"RESHARD_ENABLE": "1", "RESHARD_TIMEOUT_S": "12.5",
               "RESHARD_WORKERS": "2", "RESHARD_PORT": "8123",
               "RESHARD_PEERS": " http://a:1,http://b:2 "}
        cfg = ReshardConfig.from_env(env)
        assert cfg.enable is True
        assert cfg.timeout_s == 12.5
        assert (cfg.workers, cfg.port) == (2, 8123)
        assert cfg.peers == "http://a:1,http://b:2"

    def test_disabled_by_default_and_spellings(self):
        from dcos_commons_tpu.scheduler.elastic import ReshardConfig
        assert ReshardConfig.from_env({}).enable is False
        for raw in ("0", "false", "no", "off", ""):
            assert ReshardConfig.from_env(
                {"RESHARD_ENABLE": raw}).enable is False

    def test_validation(self):
        from dcos_commons_tpu.scheduler.elastic import ReshardConfig
        with pytest.raises(ValueError):
            ReshardConfig(timeout_s=0)
        with pytest.raises(ValueError):
            ReshardConfig(workers=0)
        with pytest.raises(ValueError):
            ReshardConfig(port=-1)


class TestReshardDrainHook:
    def test_freeze_receipt_and_emit(self):
        from dcos_commons_tpu.scheduler.elastic import reshard_drain_hook
        events = []
        hook = reshard_drain_hook(
            lambda cur, prop: {"step": 7, "from": cur, "to": prop},
            emit=events.append)
        rec = hook(4, 2)
        assert rec["reshard"] is True
        assert rec["detail"] == {"step": 7, "from": 4, "to": 2}
        assert rec["seconds"] >= 0
        assert events and events[0]["event"] == "reshard_drain"

    def test_failed_freeze_degrades_never_raises(self):
        from dcos_commons_tpu.scheduler.elastic import reshard_drain_hook

        def boom(a, b):
            raise RuntimeError("gang not at a step boundary")

        rec = reshard_drain_hook(boom)(4, 2)
        # the reshard is an optimization of the drain, never a veto:
        # the scale event proceeds down the SIGTERM/flush path
        assert rec["reshard"] is False
        assert rec["fallback"] == "sentinel-flush"
        assert "step boundary" in rec["error"]


class TestHysteresis:
    CFG = AutoscalerConfig(pod_type="decode", min_count=1, max_count=4,
                           high_pressure=0.75, low_pressure=0.25,
                           debounce_ticks=3, cooldown_ticks=2)

    def test_debounce_requires_consecutive_samples(self):
        c = HysteresisController(self.CFG)
        assert c.observe(0.9, 1) is None
        assert c.observe(0.9, 1) is None
        assert c.observe(0.9, 1) == 2      # third consecutive high

    def test_dead_band_resets_streak(self):
        c = HysteresisController(self.CFG)
        c.observe(0.9, 1)
        c.observe(0.9, 1)
        c.observe(0.5, 1)                   # dead band: streak broken
        assert c.observe(0.9, 1) is None
        assert c.observe(0.9, 1) is None
        assert c.observe(0.9, 1) == 2

    def test_cooldown_quiet_window(self):
        c = HysteresisController(self.CFG)
        for _ in range(3):
            proposed = c.observe(0.9, 1)
        assert proposed == 2
        # cooldown_ticks=2: the next two observations are swallowed even
        # at max pressure, and the debounce streak restarts after
        assert c.observe(1.0, 2) is None
        assert c.observe(1.0, 2) is None
        assert c.observe(1.0, 2) is None
        assert c.observe(1.0, 2) is None
        assert c.observe(1.0, 2) == 3

    def test_scale_down_clamped_at_min(self):
        c = HysteresisController(self.CFG)
        for _ in range(2):
            assert c.observe(0.0, 1) is None
        assert c.observe(0.0, 1) is None    # already at min: hold

    def test_scale_up_clamped_at_max(self):
        c = HysteresisController(self.CFG)
        for _ in range(2):
            assert c.observe(1.0, 4) is None
        assert c.observe(1.0, 4) is None    # already at max: hold


# ------------------------------------------------- priority on the spec

class TestPrioritySpec:
    def test_yaml_priority_parsed(self):
        spec = load_service_yaml_str(SERVE_YML)
        assert spec.priority == 10
        assert load_service_yaml_str(TRAIN_YML).priority == 1

    def test_priority_defaults_to_zero(self):
        yml = SERVE_YML.replace("priority: 10\n", "")
        assert load_service_yaml_str(yml).priority == 0


# ------------------------------------------- integration over the fleet
#
# ElasticSoak with FaultConfig.none() is a deterministic two-service
# fleet (16 chips; serve priority 10 autoscaled 1..3, train priority 1
# as a 2x4 gang) whose tick loop runs load sim -> controllers ->
# reconcile. No RNG-driven weather fires.

def quiet_soak(**kw):
    """No weather; pass ``autoscale=False`` for manual-target tests (an
    active hysteresis loop walks a forced target back down as soon as
    quiet pressure sits below the low threshold)."""
    soak = ElasticSoak(0, 0, FaultConfig.none(), **kw)
    soak._t = 0                               # continuous test clock
    return soak


def settle(soak, ticks=30, until=None, flush=True):
    """Run up to ``ticks`` quiet cycles on the soak's continuous clock
    (grace windows and burst horizons are tick arithmetic, so tests must
    never jump the clock); returns the tick the condition hit."""
    for _ in range(ticks):
        t = soak._t
        soak._t += 1
        if flush:
            soak.flushsim.flush(t, soak.cluster)
        soak.chaos.tick()
        soak._cycle(t)
        assert not soak._check(t) and not soak.violations, soak.violations
        if until is not None and until():
            return t
    assert until is None, "condition not reached"
    return soak._t


class TestAutoscalerIntegration:
    def test_scale_up_flows_through_deploy_plan(self):
        soak = quiet_soak()
        settle(soak, until=soak._converged)
        assert soak.autoscaler.target == 1
        # sustained burst: pressure > 0.7 for debounce_ticks=2 samples
        soak.load.burst(soak._t, 60)
        settle(soak, until=lambda: soak.autoscaler.target > 1)
        serve = soak.multi.get_service("serve")
        # resize is a config update: new PENDING deploy steps, and the
        # plan completes by launching the new replica
        settle(soak,
               until=lambda: serve.plan("deploy").status is Status.COMPLETE
               and soak._decode_running() >= 2)
        assert soak.autoscaler.events, "no resize event recorded"
        count, pressure = soak.autoscaler.events[0]
        assert count == 2 and pressure >= AUTOSCALE.high_pressure

    def test_scale_down_flows_through_decommission(self):
        soak = quiet_soak()
        settle(soak, until=soak._converged)
        soak.autoscaler.force_target(2)
        settle(soak, until=lambda: soak._decode_running() == 2)
        # no burst: pressure sits below 0.2, tier walks back to min
        settle(soak, until=lambda: soak.autoscaler.target == 1)
        serve = soak.multi.get_service("serve")
        settle(soak, until=lambda: soak._converged())
        assert soak._decode_running() == 1
        assert serve.decommission_manager._plan.status is Status.COMPLETE
        # the drained replica's reservation is gone
        assert not serve.ledger.for_pod("decode-1")

    def test_resize_survives_scheduler_crash(self):
        """The target lives in the persisted spec: a scheduler process
        death mid-rollout resumes to the stored count, not the boot
        count (crash-resumable acceptance)."""
        soak = quiet_soak(autoscale=False)
        settle(soak, until=soak._converged)
        soak.autoscaler.force_target(3)
        soak._restart()                       # die mid-rollout
        assert soak.autoscaler.target == 3    # read back from the store
        settle(soak, ticks=80, until=lambda: soak._decode_running() == 3)
        serve = soak.multi.get_service("serve")
        settle(soak, ticks=40,
               until=lambda: serve.plan("deploy").status is Status.COMPLETE)

    def test_force_target_clamps(self):
        soak = quiet_soak(autoscale=False)
        settle(soak, until=soak._converged)
        assert soak.autoscaler.force_target(99) == AUTOSCALE.max_count
        assert soak.autoscaler.target == AUTOSCALE.max_count


class TestPreemptorIntegration:
    @staticmethod
    def grow_to_preemption(soak):
        settle(soak, until=soak._converged)
        assert soak._train_running() == 2     # gang backfilled
        soak.autoscaler.force_target(3)       # 12 chips: must preempt
        settle(soak, ticks=60, until=lambda: soak.preemptor.records)
        return soak.preemptor.records[0]

    def test_gang_evicted_whole_with_flush_grace(self):
        soak = quiet_soak(autoscale=False)
        rec = self.grow_to_preemption(soak)
        # whole gang, never a partial slice
        assert rec.pod_instances == ("learn-0", "learn-1")
        settle(soak, ticks=60, until=lambda: not rec.inflight)
        # clean exit: flushed within grace, never escalated; reclaim
        # strictly after the terminal observation
        assert rec.escalated_tick is None
        assert rec.terminal_tick is not None
        assert rec.reclaim_tick >= rec.terminal_tick
        # both victims checkpoint-flushed (exit 143) before reclaim
        assert {inst for _, inst, _ in soak.flushsim.flushes} \
            >= set(rec.pod_instances)
        settle(soak, ticks=60, until=lambda: soak._decode_running() == 3)

    def test_preempted_gang_resumes_from_flushed_step(self):
        """Satellite: the relaunched gang resumes from the checkpointed
        step its sentinel flushed on SIGTERM, not from step 0."""
        soak = quiet_soak(autoscale=False)
        rec = self.grow_to_preemption(soak)
        settle(soak, ticks=60, until=lambda: not rec.inflight)
        flushed = {inst: step for _, inst, step in soak.flushsim.flushes}
        assert all(step > 0 for step in flushed.values()), flushed
        # scale serve back down so the gang can relaunch
        soak.autoscaler.force_target(1)
        settle(soak, ticks=80, until=lambda: soak._train_running() == 2)
        settle(soak, ticks=5)                 # let advance() observe them
        resumed = {inst: step for _, inst, step in soak.flushsim.resumes}
        for inst in rec.pod_instances:
            assert resumed.get(inst) == flushed[inst], (resumed, flushed)

    def test_grace_expiry_escalates_then_reclaims_on_killed(self):
        """A victim that never answers SIGTERM is escalated after
        grace_ticks — and reclaim still waits for the KILLED status."""
        soak = quiet_soak(autoscale=False)
        rec = self.grow_to_preemption(soak)
        # go deaf NOW: the victims' SIGTERMs are never answered (the
        # sentinel hung mid-flush), so the grace window must expire
        settle(soak, ticks=60, flush=False, until=lambda: not rec.inflight)
        assert rec.escalated_tick is not None
        assert rec.escalated_tick - rec.term_tick >= rec.grace_ticks
        assert rec.terminal_tick >= rec.escalated_tick
        assert rec.reclaim_tick >= rec.terminal_tick
        # the escalated kill is what terminated them, not a flush
        assert not soak.flushsim.flushes

    def test_priority_never_preempts_upward(self):
        """Training (priority 1) starving must not evict serving: victims
        only come from strictly lower priorities, and the floor service is
        never counted as starving."""
        soak = quiet_soak(autoscale=False)
        settle(soak, until=soak._converged)
        # occupy everything: serve@3 (12 chips) + train gang pending
        soak.autoscaler.force_target(3)
        settle(soak, ticks=80, until=lambda: soak._decode_running() == 3)
        records = list(soak.preemptor.records)
        settle(soak, ticks=20)
        # train starves (gang can't place behind the reserve) but no new
        # preemption targets serve
        assert soak.preemptor.records == records


class TestBackfillGate:
    def test_idle_chip_census(self):
        soak = quiet_soak()
        settle(soak, until=soak._converged)
        # 16 chips - serve@1 (4) - train gang (8) = 4 idle
        assert soak.backfill.idle_chips() == 4

    def test_training_gated_behind_reserve(self):
        """After preemption hands the chips to serve@3, the evicted gang
        wants back in (pending 8 chips) but only 4 are idle — the gate
        holds it out rather than letting it eat the serving reserve."""
        soak = quiet_soak(autoscale=False)
        rec = TestPreemptorIntegration.grow_to_preemption(soak)
        settle(soak, ticks=60, until=lambda: not rec.inflight)
        settle(soak, ticks=60, until=lambda: soak._decode_running() == 3)
        settle(soak, ticks=10)
        train = soak.multi.get_service("train")
        assert pending_expansion_chips(train) == 8
        assert soak.backfill.idle_chips() == 4    # 16 - serve@3 (12)
        assert not soak.backfill.may_expand("train", train)
        assert soak.backfill.gated_count > 0
        assert soak._train_running() == 0

    def test_top_priority_never_gated(self):
        soak = quiet_soak()
        settle(soak, until=soak._converged)
        serve = soak.multi.get_service("serve")
        assert soak.backfill.may_expand("serve", serve)

    def test_metrics_counters(self):
        reg = MetricsRegistry()
        soak = quiet_soak(autoscale=False)
        soak.autoscaler.metrics = reg
        soak.preemptor.metrics = reg
        soak.backfill.metrics = reg
        settle(soak, until=soak._converged)
        soak.autoscaler.force_target(3)
        settle(soak, ticks=60,
               until=lambda: soak.preemptor.records
               and not soak.preemptor.records[0].inflight)
        settle(soak, ticks=5)  # post-reclaim cycles: backfill gate fires
        counters = reg.to_dict()["counters"]
        assert counters["elastic.scale_up"] >= 1
        assert counters["elastic.preemptions"] == 1
        assert counters["elastic.preempted_pods"] == 2
        assert counters.get("elastic.backfill_gated", 0) >= 1


# -------------------------------------------------- warm pool (Round 14)

class TestWarmPool:
    def test_pool_fills_off_the_serving_path(self):
        """WARM_POOL_SIZE=1: the tier converges at serving + warm, the
        pool pod is RUNNING with zero traffic, and the autoscaler's
        bounds apply to the serving subset only."""
        soak = quiet_soak(warm_pool=1)
        settle(soak, ticks=60,
               until=lambda: soak.warmpool.available() == 1)
        pool = soak.warmpool
        assert pool.held == 1
        assert soak.autoscaler.target == 2          # serving 1 + warm 1
        assert soak.autoscaler.serving_target == 1
        assert pool.warm_instances() == ["decode-1"]
        assert pool.reclaimable_chips() == 4        # one 4-chip replica

    def test_promotion_is_one_tick_bookkeeping(self):
        """A burst promotes the warm pod the same tick the controller
        proposes the grow — the replica is ALREADY RUNNING, no deploy
        plan on the serving path; the refill that replaces it cold-boots
        off-path (so the new warm slot is not 'available' until its pod
        reports RUNNING)."""
        soak = quiet_soak(warm_pool=1)
        settle(soak, ticks=60,
               until=lambda: soak.warmpool.available() == 1)
        soak.load.burst(soak._t, 60)
        settle(soak, ticks=30,
               until=lambda: soak.autoscaler.serving_target == 2)
        pool = soak.warmpool
        assert pool.promoted == ["decode-1"]
        # the promoted replica was serving the tick the boundary moved
        assert soak._decode_running() >= 2
        # refill already re-booked the slot, but a deploying pod is a
        # cold boot in disguise: not promotable until RUNNING
        assert pool.held == 1
        assert pool.available() == 0

    def test_promote_demote_boundary_arithmetic(self):
        """Promotion/demotion slide the serving/warm boundary without
        touching the config actuator, bounded by pool room and the
        min_serving floor."""
        soak = quiet_soak(warm_pool=1)
        settle(soak, ticks=60,
               until=lambda: soak.warmpool.available() == 1)
        pool = soak.warmpool
        assert pool.demote(1) == 0     # pool full: nowhere to park
        assert pool.promote(1) == 1    # bookkeeping only
        assert pool.held == 0 and pool.deficit() == 1
        assert pool.demote(1) == 1     # the mirror image: park it back
        assert pool.held == 1
        assert pool.promote(0) == 0

    def test_rederive_after_scheduler_crash(self):
        """The serving/warm split is controller memory: after a crash
        the rewired controller rebuilds a conservative split from the
        persisted pod count (never over-counting serving)."""
        soak = quiet_soak(warm_pool=1)
        settle(soak, ticks=60,
               until=lambda: soak.warmpool.available() == 1)
        soak._restart()
        assert soak.warmpool.held == 1   # count 2 - min_serving 1


# --------------------------------------------- auto reserve (Round 14)

class _StubPool:
    def __init__(self, chips):
        self._chips = chips

    def reclaimable_chips(self):
        return self._chips


class TestBackfillAutoReserve:
    def test_rolling_max_replaces_static_reserve(self):
        gate = BackfillGate(lambda: None, reserve_chips=8,
                            auto_reserve=True, reserve_window=3)
        assert gate.current_reserve() == 8   # fallback pre-observation
        gate.observe(4)
        gate.observe(16)
        gate.observe(2)
        assert gate.current_reserve() == 16
        gate.observe(1)
        gate.observe(1)                      # 16 rolls out of the window
        assert gate.current_reserve() == 2

    def test_static_reserve_when_auto_off(self):
        gate = BackfillGate(lambda: None, reserve_chips=5)
        gate.observe(99)
        assert gate.current_reserve() == 5

    def test_warm_pool_offsets_the_reserve(self):
        """The pool's one-tick-reclaimable chips are headroom the
        serving tier already holds — demanding them again as idle would
        double-reserve."""
        gate = BackfillGate(lambda: None, reserve_chips=10,
                            warm_pool=_StubPool(6))
        assert gate.effective_reserve() == 4
        gate.warm_pool = _StubPool(50)
        assert gate.effective_reserve() == 0   # clamped, never negative


# ------------------------------------------- rolling-window load gauges

class _StubEngine:
    slots = 2

    def free_slots(self):
        return [0, 1]


class TestLoadGauges:
    def make(self, **kw):
        return ServingFrontend(_StubEngine(), port=0, host="127.0.0.1",
                               max_queue=8, **kw)

    def test_gauge_shape_matches_autoscaler_contract(self):
        fe = self.make()
        g = fe.load_gauges()
        assert set(g) >= {"window_s", "queue_depth", "queue_capacity",
                          "completed", "shed", "shed_rate", "ttft_p95_ms"}
        assert g["queue_capacity"] == 8
        assert g["shed"] == 0 and g["completed"] == 0
        assert backpressure(g) == 0.0

    def test_window_expires_old_samples(self):
        import time as _time
        fe = self.make(window_s=60.0)
        now = _time.monotonic()
        with fe._lock:
            fe._sheds.append(now - 120)           # outside the window
            fe._sheds.append(now - 1)             # inside
            fe._window.append((now - 120, 5.0, 1.0))
            fe._window.append((now - 2, 10.0, 1.0))
            fe._window.append((now - 1, 30.0, 2.0))
        g = fe.load_gauges()
        assert g["shed"] == 1
        assert g["completed"] == 2
        assert g["shed_rate"] == pytest.approx(1 / 3)
        assert g["ttft_p95_ms"] is not None

    def test_shedding_drives_backpressure(self):
        import time as _time
        fe = self.make()
        with fe._lock:
            fe._sheds.append(_time.monotonic())
        assert backpressure(fe.load_gauges()) == 1.0

    def test_healthz_and_stats_carry_the_window(self):
        fe = self.make()
        assert fe.health()["load"] == fe.load_gauges()
        assert fe.stats()["window"] == fe.load_gauges()


# ------------------------------------- env wiring over live frontends
#
# Satellite coverage for the framework-main path: autoscaler_from_env
# arms an Autoscaler whose gauges_fn polls REAL ServingFrontend
# /v1/healthz endpoints over HTTP (http_gauges), adapted onto a solo
# ServiceScheduler through SoloService.

ELASTIC_YML = """
name: elastisvc
pods:
  decode:
    count: 1
    tasks:
      server: {goal: RUNNING, cmd: ./serve, cpus: 0.1, memory: 64}
"""


def make_solo_scheduler():
    from dcos_commons_tpu.agent import AgentInfo, FakeCluster, PortRange
    from dcos_commons_tpu.scheduler import ServiceScheduler
    from dcos_commons_tpu.state import MemPersister
    agents = [AgentInfo(agent_id="a0", hostname="h0", cpus=8,
                        memory_mb=16384, disk_mb=10000,
                        ports=(PortRange(10000, 10100),))]
    return ServiceScheduler(load_service_yaml_str(ELASTIC_YML),
                            MemPersister(), FakeCluster(agents))


class TestAutoscalerEnvWiring:
    def test_inert_without_env(self):
        from dcos_commons_tpu.scheduler.elastic import autoscaler_from_env
        sched = make_solo_scheduler()
        assert autoscaler_from_env(sched, env={}) is None
        assert autoscaler_from_env(
            sched, env={"AUTOSCALE_POD_TYPE": "decode"}) is None
        assert autoscaler_from_env(
            sched, env={"AUTOSCALE_GAUGE_URLS": "http://x"}) is None

    def test_solo_service_adapter(self):
        from dcos_commons_tpu.scheduler.elastic import SoloService
        sched = make_solo_scheduler()
        solo = SoloService(sched)
        assert solo.get_service("anything") is sched
        solo.service_store.store(sched.spec)    # durable no-op

    def test_http_gauges_merge_live_frontends(self):
        from dcos_commons_tpu.scheduler.elastic import http_gauges
        frontends = [ServingFrontend(_StubEngine(), port=0,
                                     host="127.0.0.1", max_queue=8)
                     .start(drive=False) for _ in range(2)]
        try:
            urls = [f"http://127.0.0.1:{fe.port}" for fe in frontends]
            # a dead replica is skipped, not fatal
            gauges = http_gauges(urls + ["http://127.0.0.1:9"],
                                 timeout_s=2.0)()
            assert gauges["replicas_polled"] == 2
            assert gauges["queue_capacity"] == 16    # 8 + 8, summed
            assert gauges["queue_depth"] == 0
            assert gauges["shed_rate"] == 0.0
            assert backpressure(gauges) == 0.0
        finally:
            for fe in frontends:
                fe.stop()

    def test_env_autoscaler_scales_on_live_pressure(self):
        """End to end: shed pressure visible on a real frontend's
        /v1/healthz drives the env-wired autoscaler to grow the decode
        tier of a real (solo) scheduler through its deploy plan."""
        import time as _time

        from dcos_commons_tpu.scheduler.elastic import autoscaler_from_env
        sched = make_solo_scheduler()
        sched.run_until_quiet()
        fe = ServingFrontend(_StubEngine(), port=0, host="127.0.0.1",
                             max_queue=8).start(drive=False)
        try:
            auto = autoscaler_from_env(sched, env={
                "AUTOSCALE_POD_TYPE": "decode",
                "AUTOSCALE_GAUGE_URLS": f"http://127.0.0.1:{fe.port}",
                "AUTOSCALE_DEBOUNCE": "2",
                "AUTOSCALE_COOLDOWN": "1",
            })
            assert auto is not None and auto.target == 1
            assert auto.tick() is None          # quiet fleet: hold
            assert auto.last_pressure == 0.0
            # a shed in the rolling window pins pressure to 1.0
            with fe._lock:
                fe._sheds.append(_time.monotonic())
            assert auto.tick() is None          # debounce sample 1
            assert auto.last_pressure == 1.0
            assert auto.tick() == 2             # sample 2: resize accepted
            assert auto.target == 2             # read back from the spec
            assert auto.events == [(2, 1.0)]
            # the resize is a config update: the deploy plan launches the
            # new replica on the next cycles
            sched.run_until_quiet()
            live = [t for t in sched.cluster.live_tasks()
                    if t.task_name.startswith("decode-")]
            assert len(live) == 2
        finally:
            fe.stop()
